"""Multi-tenant crypto-as-a-service: one shared device frontier serving
many chains.

The reference runs one consensus process per chain (PAPER.md §0), so one
chain = one crypto backend.  The TPU inverts those economics: a single
chip at ~20.8k verifies/s (BENCH_r05) can carry dozens of chains' vote
traffic — but until this module, each engine built its own private
``BatchingVerifier`` and chains "shared" the device only by accident of
serialization: no fairness, no priority, and an unbounded pending queue
under saturation.

``SharedFrontier`` makes sharing the chip a first-class subsystem.  N
tenants (chains/engines, in-process) ``register()`` lanes that feed one
batching core:

  fairness    each flush is composed by deficit-weighted round-robin
              across tenants (``tenant_weight`` entries per cycle, the
              deficit carrying over when a batch cap cuts a turn short,
              the rotation start advancing every flush) — a tenant
              flooding its lane cannot push other tenants' requests out
              of a batch, only fill the slack they don't use
  priority    two classes per tenant: *critical* (proposal-path
              verifies — a late proposal stalls the whole round) and
              *gossip* (vote/choke verifies — late ones cost one vote's
              latency).  Within a tenant's turn the critical queue
              always drains first
  admission   per-tenant queues are bounded (``queue_bound``).  Arrivals
              over the bound are not dropped and not queued: they are
              **shed to the host-oracle verify path** —
              ``provider.verify_signature``, the exact same host twin
              the PR 2 circuit breaker falls back to — so correctness
              is never traded for flow control, only device batching.
              Sheds count into ``frontier_admission_sheds_total{tenant}``

plus per-tenant observability: queue-wait histograms split by class
(``frontier_tenant_queue_wait_ms{tenant,lane}``), batch occupancy share
(``frontier_tenant_lanes_total`` / ``frontier_tenant_share``), and a
``tenants_status()`` snapshot for the /statusz "tenants" section.

``BatchingVerifier`` (crypto/frontier.py) is now a single-tenant lane
over a core it owns, so the existing service/sim/bench paths ride this
code — and inherit the bounded-queue shed (the stalled-device fix):
before, a wedged device let pending verifies grow without limit.

The dispatch machinery is unchanged from the proven single-tenant
frontier: one dedicated dispatch worker keeps device dispatch order
FIFO across flushes (a cold jit compile or remote-PJRT H2D never stalls
the event loop), readback blocks only a resolver thread, and a failed
batch re-verifies every lane on the host oracle with exact verdicts.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import Counter as _Counter
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from ..core.sm3 import sm3_hash
from ..core.types import SignedChoke, SignedProposal, SignedVote
from ..obs.fleet import next_round_id, tag_round
from ..obs.prof import annotate

logger = logging.getLogger("consensus_overlord_tpu.tenancy")

__all__ = [
    "DEFAULT_QUEUE_BOUND",
    "FrontierStats",
    "SharedFrontier",
    "TenantLane",
    "TenantStats",
    "signature_claims",
]

#: Default per-tenant pending bound: 8× the default max_batch — deep
#: enough that a healthy device never sheds (it drains max_batch per
#: flush), shallow enough that a stalled device sheds to the host
#: oracle instead of accumulating unbounded futures.
DEFAULT_QUEUE_BOUND = 8192

#: Recent queue-wait samples kept per tenant for the /statusz p50 (the
#: full distributions live in the Prometheus histograms).
WAIT_WINDOW = 512


def signature_claims(msg) -> Optional[Tuple[bytes, bytes, bytes]]:
    """(signature, hash32, voter) claimed by an inbound consensus message,
    or None for message types verified elsewhere (QCs carry aggregated
    signatures checked in the engine against the voter bitmap)."""
    if isinstance(msg, SignedProposal):
        return (msg.signature, sm3_hash(msg.proposal.encode()),
                msg.proposal.proposer)
    if isinstance(msg, SignedVote):
        return msg.signature, sm3_hash(msg.vote.encode()), msg.voter
    if isinstance(msg, SignedChoke):
        return msg.signature, sm3_hash(msg.choke.encode()), msg.address
    return None


def is_critical(msg) -> bool:
    """Proposal-path verifies are critical: one late proposal stalls the
    whole round for every honest node, while a late vote costs only that
    vote's latency (the QC needs 2f+1 of n anyway)."""
    return isinstance(msg, SignedProposal)


@dataclass
class FrontierStats:
    """Whole-core counters (the single-tenant frontier's legacy shape —
    /statusz "frontier" and the bench scripts read these).  `requests`
    counts only batched-path requests so `mean_batch` keeps its meaning
    under shedding; shed requests count in `sheds` (total arrivals =
    requests + sheds)."""

    requests: int = 0
    batches: int = 0
    max_batch: int = 0
    failures: int = 0
    sheds: int = 0

    @property
    def mean_batch(self) -> float:
        return self.requests / self.batches if self.batches else 0.0


@dataclass
class TenantStats:
    """One tenant's counters + a bounded queue-wait window."""

    requests: int = 0
    critical_requests: int = 0
    sheds: int = 0
    failures: int = 0
    #: Device-batch lanes this tenant's requests filled (its share of
    #: the chip; compare across tenants for occupancy fairness).
    lanes_contributed: int = 0
    waits: Deque[Tuple[float, bool]] = field(
        default_factory=lambda: deque(maxlen=WAIT_WINDOW))

    def record_wait(self, wait_s: float, critical: bool) -> None:
        self.waits.append((wait_s, critical))

    def p50_wait_ms(self, critical: Optional[bool] = None) -> Optional[float]:
        """Median recent queue wait in ms (critical=True/False filters to
        one class; None spans both), or None with no samples yet."""
        samples = sorted(w for w, c in self.waits
                         if critical is None or c == critical)
        if not samples:
            return None
        return samples[len(samples) // 2] * 1000.0


class TenantLane:
    """One tenant's handle onto a SharedFrontier: the frontier interface
    the engine consumes (verify / verify_msg / verify_aggregated /
    aggregate), scoped to this tenant's queues, weight, and bound.

    A lane may be shared by every validator of one chain (the tenant =
    the chain): queues, stats, and fairness are per-tenant, not
    per-caller.  ``close()`` is a no-op — the shared core outlives any
    one lane; the core's owner closes it (``BatchingVerifier``, which
    owns its core, overrides this)."""

    def __init__(self, core: "SharedFrontier", tenant_id: str,
                 weight: int = 1, queue_bound: int = DEFAULT_QUEUE_BOUND,
                 priority_lanes: bool = True):
        if weight < 1:
            raise ValueError(f"tenant weight must be >= 1, got {weight}")
        if queue_bound < 1:
            raise ValueError(
                f"tenant queue bound must be >= 1, got {queue_bound}")
        self._core = core
        self.tenant_id = str(tenant_id)
        self.weight = int(weight)
        self.queue_bound = int(queue_bound)
        self.priority_lanes = bool(priority_lanes)
        self.tenant_stats = TenantStats()
        #: DWRR deficit: carries over when a batch cap cuts this
        #: tenant's turn short, so the shortfall is repaid next flush.
        self._deficit = 0.0
        #: Pending entries by class; composed into device batches by the
        #: core's DWRR pass (critical always pops first).
        self._critical: Deque[tuple] = deque()
        self._gossip: Deque[tuple] = deque()
        #: Entries composed into a device batch whose futures have not
        #: resolved yet.  They count toward the admission bound: a
        #: stalled device drains the WAITING queue at every flush but
        #: leaves these accumulating — without them in the bound, the
        #: unbounded-growth failure just moves from pending to in-flight.
        self._in_flight = 0

    # -- queue plumbing (called by the core under the event loop) ----------

    def pending_count(self) -> int:
        return len(self._critical) + len(self._gossip)

    def outstanding_count(self) -> int:
        """Waiting + composed-but-unresolved — what the admission bound
        actually limits (the tenant's total unresolved futures)."""
        return self.pending_count() + self._in_flight

    def _pop_next(self) -> tuple:
        return self._critical.popleft() if self._critical \
            else self._gossip.popleft()

    # -- stats -------------------------------------------------------------

    @property
    def stats(self) -> TenantStats:
        return self.tenant_stats

    def status(self) -> dict:
        """JSON-encodable snapshot for /statusz "tenants"."""
        s = self.tenant_stats
        return {
            "weight": self.weight,
            "queue_bound": self.queue_bound,
            "priority_lanes": self.priority_lanes,
            "queued": self.pending_count(),
            "queued_critical": len(self._critical),
            "in_flight": self._in_flight,
            "requests": s.requests,
            "critical_requests": s.critical_requests,
            "sheds": s.sheds,
            "failures": s.failures,
            "lanes_contributed": s.lanes_contributed,
            "p50_wait_ms": s.p50_wait_ms(),
            "p50_critical_wait_ms": s.p50_wait_ms(critical=True),
        }

    # -- the frontier interface (what the engine consumes) -----------------

    async def verify(self, signature: bytes, hash32: bytes, voter: bytes,
                     msg_type: str = "raw", critical: bool = False) -> bool:
        if critical and not self.priority_lanes:
            critical = False
        return await self._core.submit(self, bytes(signature), bytes(hash32),
                                       bytes(voter), msg_type, critical)

    async def verify_msg(self, msg) -> bool:
        """Verify a decoded consensus message's signature claim; True for
        message types with no frontier-checkable signature.  Proposals
        ride the critical class (see is_critical)."""
        claims = signature_claims(msg)
        if claims is None:
            return True
        return await self.verify(*claims, msg_type=type(msg).__name__,
                                 critical=is_critical(msg))

    def verify_msg_nowait(self, msg):
        """Sync-admission twin of verify_msg: ``True`` when the message
        carries no frontier-checkable claim, else an awaitable verdict
        whose claim is ALREADY enqueued at the core (see
        SharedFrontier.submit_nowait).  The sim fabric's per-tick batch
        injection submits every claim in a delivery pass before awaiting
        any, so one linger window covers the whole pass."""
        claims = signature_claims(msg)
        if claims is None:
            return True
        signature, hash32, voter = claims
        critical = is_critical(msg) and self.priority_lanes
        return self._core.submit_nowait(
            self, bytes(signature), bytes(hash32), bytes(voter),
            type(msg).__name__, critical)

    async def verify_aggregated(self, agg_sig: bytes, hash32: bytes,
                                voters) -> bool:
        return await self._core.verify_aggregated(agg_sig, hash32, voters)

    async def aggregate(self, signatures, voters) -> bytes:
        return await self._core.aggregate(signatures, voters)

    @property
    def last_agg_round_id(self) -> Optional[int]:
        """Round id of the core's most recent aggregate-path dispatch
        (the engine reads it through its lane handle right after a QC
        verify/aggregate await to link the commit trace — see
        SharedFrontier.last_agg_round_id)."""
        return self._core.last_agg_round_id

    def close(self) -> None:
        """Lanes don't own the core (see class docstring)."""

    def tenants_status(self) -> dict:
        """Mirror the core's tenant snapshot (so a lane handle can serve
        the /statusz "tenants" section directly)."""
        return self._core.tenants_status()


class SharedFrontier:
    """The shared device batching core N tenant lanes feed.

    provider: the crypto backend every composed batch dispatches
    through (``verify_batch`` / ``verify_batch_async``); its
    ``verify_signature`` host oracle serves the shed and batch-error
    fallbacks (for TpuBlsCrypto that is the CPU pairing backend — the
    PR 2 breaker fallback machinery).
    max_batch: flush immediately at this many pending entries across
    all tenants (the device pad-ladder cap).
    linger_s: how long the first pending request waits for company.
    metrics: optional obs.Metrics — per-tenant families carry the
    tenant label; None = zero overhead.
    """

    def __init__(self, provider, max_batch: int = 1024,
                 linger_s: float = 0.002, metrics=None, recorder=None):
        self._provider = provider
        self._max_batch = int(max_batch)
        self._linger = linger_s
        self._metrics = metrics
        #: Optional obs.FlightRecorder: each flush records a
        #: `round_flush` event carrying the round id the dispatch is
        #: tagged with (obs/fleet.py) — the waterfall's anchor event.
        self._recorder = recorder
        self._lanes: Dict[str, TenantLane] = {}
        #: Registration order = DWRR rotation order; the start position
        #: advances every flush so no tenant owns the batch head.
        self._order: List[TenantLane] = []
        self._rr_cursor = 0
        self._total_pending = 0
        self._flush_task: Optional[asyncio.Task] = None
        # asyncio holds only weak refs to tasks; in-flight batch tasks
        # must be pinned or GC can collect one mid-verify, hanging every
        # waiter.
        self._inflight: set = set()
        # One dedicated dispatch worker: device dispatches (which may
        # block on a cold jit compile — minutes for a new batch shape —
        # or on H2D transfers over a remote PJRT link) run OFF the event
        # loop, and the single worker keeps dispatch order FIFO across
        # flushes so pipelining stays deterministic.
        self._dispatcher = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="frontier-dispatch")
        #: Chaos stall window (sim/chaos.py `tenant_stall`): monotonic
        #: deadline before which composed batches sleep instead of
        #: dispatching — the wedged-shared-chip failure mode.  Only the
        #: batch path stalls; QC aggregate verifies have no bounded
        #: shed alternative and stalling them would wedge consensus
        #: outright rather than exercise flow control.
        self._stall_until = 0.0
        #: Round id (obs/fleet.py) of the most recent QC aggregate-path
        #: dispatch (verify_aggregated / aggregate): the causal commit
        #: tracer reads it right after its await resolves, linking the
        #: commit trace's qc_verify stage to the device-profile ring
        #: records the dispatch produced (scripts/waterfall.py joins
        #: both streams on the id).  Best-effort under concurrency —
        #: provenance, not accounting.
        self.last_agg_round_id: Optional[int] = None
        self.stats = FrontierStats()

    # -- tenancy -----------------------------------------------------------

    def register(self, tenant_id: str, weight: int = 1,
                 queue_bound: int = DEFAULT_QUEUE_BOUND,
                 priority_lanes: bool = True) -> TenantLane:
        """Register a tenant; returns its lane.  Registering an existing
        id returns the existing lane unchanged (a chain's validators all
        feed one tenant)."""
        lane = self._lanes.get(str(tenant_id))
        if lane is not None:
            return lane
        return self.adopt(TenantLane(self, tenant_id, weight=weight,
                                     queue_bound=queue_bound,
                                     priority_lanes=priority_lanes))

    def adopt(self, lane: TenantLane) -> TenantLane:
        """Attach an externally-constructed lane (register()'s
        bookkeeping twin — BatchingVerifier adopts ITSELF, being both
        the lane subclass and the core's owner).  One registration site
        for all lane kinds, so future register-side bookkeeping can't
        silently skip the single-tenant path."""
        if lane.tenant_id in self._lanes:
            raise ValueError(f"tenant {lane.tenant_id!r} already "
                             "registered")
        self._lanes[lane.tenant_id] = lane
        self._order.append(lane)
        return lane

    @property
    def tenants(self) -> Dict[str, TenantLane]:
        return dict(self._lanes)

    def inject_stall(self, duration_s: float) -> None:
        """Arm a device-stall window (chaos `tenant_stall`): for
        `duration_s` from now every composed batch sleeps before
        dispatching, so queues back up and the bounded admission path
        must shed to the host oracle — correctness survives a wedged
        shared chip through flow control, not luck.  Overlapping
        windows extend, never shorten."""
        self._stall_until = max(self._stall_until,
                                time.monotonic() + float(duration_s))
        logger.warning("frontier: chaos stall armed for %.2fs",
                       duration_s)

    @property
    def stall_injected(self) -> bool:
        return time.monotonic() < self._stall_until

    def tenants_status(self) -> dict:
        """Per-tenant snapshot for the /statusz "tenants" section."""
        return {tid: lane.status() for tid, lane in self._lanes.items()}

    # -- admission + enqueue -----------------------------------------------

    async def submit(self, lane: TenantLane, signature: bytes, hash32: bytes,
                     voter: bytes, msg_type: str, critical: bool) -> bool:
        """One tenant verify: enqueue under the bound, shed over it."""
        return await self.submit_nowait(lane, signature, hash32, voter,
                                        msg_type, critical)

    def submit_nowait(self, lane: TenantLane, signature: bytes,
                      hash32: bytes, voter: bytes, msg_type: str,
                      critical: bool):
        """Sync-admission submit: bookkeeping and enqueue happen on the
        caller's loop slice; the verdict comes back as an awaitable (the
        entry future — or the shed coroutine on bound overflow).  Batch
        callers submit every claim first, then await, so one linger
        window covers them all instead of one per message.

        The bound counts OUTSTANDING work (waiting + composed-but-
        unresolved): composition drains the waiting queue at every
        flush whatever the device is doing, so a pending-only bound
        would never engage under the stalled device it exists for."""
        lane.tenant_stats.requests += 1
        if critical:
            lane.tenant_stats.critical_requests += 1
        if lane.outstanding_count() >= lane.queue_bound:
            self.stats.sheds += 1
            return self._shed(lane, signature, hash32, voter, msg_type)
        self.stats.requests += 1
        fut = asyncio.get_running_loop().create_future()
        entry = (signature, hash32, voter, fut, msg_type,
                 time.perf_counter(), lane, critical)
        (lane._critical if critical else lane._gossip).append(entry)
        self._total_pending += 1
        if self._total_pending >= self._max_batch:
            self._flush_now("max_batch")
        elif self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_running_loop().create_task(
                self._linger_then_flush())
        return fut

    async def _shed(self, lane: TenantLane, signature: bytes, hash32: bytes,
                    voter: bytes, msg_type: str) -> bool:
        """Admission-control overflow: verify on the host oracle instead
        of queueing for the device.  The verdict is exact (the oracle is
        the breaker's fallback twin), so shedding costs device batching
        efficiency, never correctness."""
        lane.tenant_stats.sheds += 1
        m = self._metrics
        if m is not None:
            m.frontier_admission_sheds.labels(
                tenant=lane.tenant_id).inc()
        errored = False
        try:
            ok = bool(await asyncio.to_thread(
                self._provider.verify_signature, signature, hash32, voter))
        except Exception:  # noqa: BLE001 — malformed input is never fatal
            logger.exception("shed host verify errored (tenant %s)",
                             lane.tenant_id)
            ok = False
            errored = True
            if m is not None:
                # Same posture as the batch path's "batch_error": an
                # oracle infra error must not masquerade as a
                # per-message signature attack.
                m.frontier_verify_failures.labels(
                    msg_type="shed_error").inc()
        if not ok:
            self.stats.failures += 1
            lane.tenant_stats.failures += 1
            if m is not None and not errored:
                m.frontier_verify_failures.labels(msg_type=msg_type).inc()
        return ok

    # -- aggregate paths (shared ordered dispatcher) -----------------------

    async def verify_aggregated(self, agg_sig: bytes, hash32: bytes,
                                voters) -> bool:
        """QC aggregate verification off the event loop: dispatch through
        the same single ordered worker as batch flushes (device FIFO
        stays intact), block only in a resolver thread.  Like _run_batch
        the dispatch is round-tagged, so the device-profile ring records
        it produces join the commit trace's qc_verify stage on the id."""
        dispatch = getattr(self._provider, "verify_aggregated_async", None)
        round_id = next_round_id()
        self.last_agg_round_id = round_id
        try:
            if dispatch is None:
                def _host():
                    with tag_round(round_id):
                        return self._provider.verify_aggregated_signature(
                            agg_sig, hash32, voters)
                return await asyncio.to_thread(_host)
            return await self._via_dispatcher(dispatch, agg_sig, hash32,
                                              voters, round_id=round_id)
        except Exception:  # noqa: BLE001 — malformed input is never fatal
            logger.exception("frontier QC verification errored")
            return False

    async def aggregate(self, signatures, voters) -> bytes:
        """QC signature aggregation off the event loop (leader path).
        Raises CryptoError on invalid input, like the sync form."""
        dispatch = getattr(self._provider, "aggregate_signatures_async",
                           None)
        round_id = next_round_id()
        self.last_agg_round_id = round_id
        if dispatch is None:
            def _host():
                with tag_round(round_id):
                    return self._provider.aggregate_signatures(signatures,
                                                               voters)
            return await asyncio.to_thread(_host)
        return await self._via_dispatcher(dispatch, signatures, voters,
                                          round_id=round_id)

    async def _via_dispatcher(self, dispatch, *args, round_id=None):
        """dispatch(*args) on the ordered worker → resolve() in a second
        thread (overlaps the dispatch→readback round-trip with device
        compute, same pipeline as _run_batch).  round_id tags both
        threads (thread-local, like _run_batch) so profiler records
        land under it."""
        loop = asyncio.get_running_loop()

        def _dispatch():
            with tag_round(round_id):
                return dispatch(*args)

        resolver = await loop.run_in_executor(self._dispatcher, _dispatch)

        def _resolve():
            with tag_round(round_id):
                return resolver()

        return await asyncio.to_thread(_resolve)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Release the dispatch worker thread (engine/sim teardown).
        Still-pending requests are flushed first (reason="shutdown") so
        their futures resolve instead of hanging their awaiters — only
        possible from a running event loop (the normal teardown path).
        The worker shuts down only after in-flight batch tasks (incl. a
        shutdown flush) have dispatched through it — shutting it down
        eagerly would bounce those batches onto the per-signature host
        re-verify fallback (RuntimeError from run_in_executor)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:  # no loop: nothing can await those futures
            loop = None
            for lane in self._order:
                lane._critical.clear()
                lane._gossip.clear()
            self._total_pending = 0
        if self._total_pending:
            self._flush_now("shutdown")
        if loop is not None and self._inflight:
            dispatcher = self._dispatcher

            async def _drain_then_release(tasks):
                try:
                    await asyncio.gather(*tasks, return_exceptions=True)
                finally:
                    # Loop teardown can cancel this task mid-gather; the
                    # worker thread must be released regardless or each
                    # closed frontier leaks one non-daemon thread.
                    dispatcher.shutdown(wait=False)

            # Pinned in _inflight: asyncio holds only weak task refs
            # (see __init__) — an unpinned drain task can be GC'd
            # mid-await, leaking the worker thread.
            task = loop.create_task(_drain_then_release(
                list(self._inflight)))
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)
        else:
            self._dispatcher.shutdown(wait=False)

    # -- flush machinery ---------------------------------------------------

    async def _linger_then_flush(self) -> None:
        await asyncio.sleep(self._linger)
        self._flush_now("linger")

    def _compose_batch(self) -> List[tuple]:
        """Deficit-weighted round robin across tenants with pending work,
        up to max_batch entries.  Each cycle a tenant earns `weight`
        slots; within its turn the critical queue drains first.  The
        deficit persists across flushes (a turn cut short by the batch
        cap is repaid next flush) and the rotation start advances every
        compose, so no tenant systematically owns the batch head."""
        n = len(self._order)
        if n == 0:
            return []
        start = self._rr_cursor % n
        self._rr_cursor += 1
        active = deque(lane for lane in
                       (self._order[start:] + self._order[:start])
                       if lane.pending_count() > 0)
        batch: List[tuple] = []
        while active and len(batch) < self._max_batch:
            lane = active.popleft()
            lane._deficit += lane.weight
            while (lane._deficit >= 1 and lane.pending_count() > 0
                   and len(batch) < self._max_batch):
                batch.append(lane._pop_next())
                lane._in_flight += 1
                lane._deficit -= 1
            if lane.pending_count() == 0:
                # Standard DWRR: an emptied queue forfeits its credit
                # (or an idle tenant would bank unbounded burst rights).
                lane._deficit = 0.0
            elif len(batch) < self._max_batch:
                active.append(lane)
            # Batch full with this lane still pending: its deficit
            # carries over — the next flush repays the cut-short turn.
        self._total_pending -= len(batch)
        return batch

    def _flush_now(self, reason: str) -> None:
        if self._flush_task is not None and not self._flush_task.done():
            self._flush_task.cancel()
        self._flush_task = None
        while self._total_pending > 0:
            batch = self._compose_batch()
            if not batch:
                break
            if self._metrics is not None:
                # Why the batch left the frontier: linger-expired vs
                # max-batch vs shutdown drain — without this the
                # queue-wait histogram is uninterpretable (a long wait
                # is EXPECTED under linger flushes, a red flag under
                # max-batch ones).
                self._metrics.frontier_flush_reason.labels(
                    reason=reason).inc()
            task = asyncio.get_running_loop().create_task(
                self._run_batch(batch))
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)
            # Shutdown drains everything; normal flushes leave a
            # sub-max_batch remainder to the next linger window.
            if reason != "shutdown" and self._total_pending < self._max_batch:
                break
        if self._total_pending > 0 and reason != "shutdown":
            self._flush_task = asyncio.get_running_loop().create_task(
                self._linger_then_flush())

    def _account_batch(self, batch: List[tuple]) -> None:
        """Per-tenant occupancy share of one composed batch."""
        counts = _Counter(e[6] for e in batch)
        m = self._metrics
        for lane, c in counts.items():
            lane.tenant_stats.lanes_contributed += c
            if m is not None:
                m.frontier_tenant_lanes.labels(tenant=lane.tenant_id).inc(c)
        if m is not None:
            # Every registered tenant gets a share of THIS batch (absent
            # tenants explicitly 0) — a stale gauge from a batch a
            # tenant last rode would make the shares sum past 1 exactly
            # when load is skewed, the moment the gauge exists for.
            for lane in self._order:
                m.frontier_tenant_share.labels(tenant=lane.tenant_id).set(
                    counts.get(lane, 0) / len(batch))

    async def _run_batch(self, batch: List[tuple]) -> None:
        stall = self._stall_until - time.monotonic()
        if stall > 0:
            # Chaos tenant_stall: the "device" is wedged — hold the
            # composed batch (waiters included) until the window ends.
            await asyncio.sleep(stall)
        sigs = [b[0] for b in batch]
        hashes = [b[1] for b in batch]
        voters = [b[2] for b in batch]
        m = self._metrics
        # One round id per flush: the dispatcher thread is tagged with
        # it (a thread-local — run_in_executor does not carry
        # contextvars), so every StagedCall / per-device sample /
        # flightrec event this flush produces joins on it
        # (scripts/waterfall.py).
        round_id = next_round_id()
        if self._recorder is not None:
            now = time.perf_counter()
            oldest = min((b[5] for b in batch), default=now)
            self._recorder.record(
                "round_flush", round_id=round_id, batch=len(batch),
                queue_wait_s=round(max(now - oldest, 0.0), 6))
        self._account_batch(batch)
        if m is not None:
            # Batch size only; padded-rung occupancy is observed by the
            # provider at host-prep time (crypto/tpu_provider.py), where
            # the pad sizes are actually computed — one source of truth
            # across the fused/split dispatch plans.
            m.frontier_batch_size.observe(len(batch))
        try:
            verify_async = getattr(self._provider, "verify_batch_async",
                                   None)
            if verify_async is not None:
                # Dispatch through the single ordered worker (off-loop:
                # a cold compile or H2D transfer never stalls consensus
                # timers), then block only for the readback in a second
                # thread — consecutive flushes overlap the ~200 ms
                # dispatch→readback round-trip of a remote PJRT link
                # with device compute.
                loop = asyncio.get_running_loop()

                def _dispatch():
                    with tag_round(round_id):
                        return verify_async(sigs, hashes, voters)

                t0 = time.perf_counter()
                with annotate("frontier.flush"):
                    resolver = await loop.run_in_executor(
                        self._dispatcher, _dispatch)
                t1 = time.perf_counter()

                def _resolve():
                    # Readback/pairing (and the throttled per-device
                    # skew sample) run here — same round tag.
                    with tag_round(round_id):
                        return resolver()

                results = await asyncio.to_thread(_resolve)
                if m is not None:
                    # frontier_* phases are wrappers AROUND the provider's
                    # prep/dispatch/readback/pairing phases (they include
                    # executor queueing), distinct labels so the series
                    # compose instead of double-counting.
                    t2 = time.perf_counter()
                    m.crypto_dispatch_ms.labels(
                        phase="frontier_dispatch").observe(
                        (t1 - t0) * 1000.0)
                    m.crypto_dispatch_ms.labels(
                        phase="frontier_resolve").observe(
                        (t2 - t1) * 1000.0)
            else:
                # Device dispatch blocks; keep the event loop live.
                def _verify():
                    with tag_round(round_id):
                        return self._provider.verify_batch(sigs, hashes,
                                                           voters)

                t0 = time.perf_counter()
                results = await asyncio.to_thread(_verify)
                if m is not None:
                    m.crypto_dispatch_ms.labels(
                        phase="frontier_resolve").observe(
                        (time.perf_counter() - t0) * 1000.0)
            errored = False
        except Exception:  # noqa: BLE001 — malformed input is never fatal
            # A provider whose device path died mid-batch (and that has
            # no internal breaker/fallback of its own): re-verify every
            # lane on the host oracle — consensus keeps making progress
            # on exact verdicts instead of dropping a whole batch of
            # honest votes as if they were forged.
            logger.exception(
                "frontier batch verification errored; host re-verify")
            if m is not None:
                m.host_fallbacks.labels(path="frontier_reverify").inc()
            try:
                results = await asyncio.to_thread(
                    lambda: [self._provider.verify_signature(s, h, v)
                             for s, h, v in zip(sigs, hashes, voters)])
                errored = False
            except Exception:  # noqa: BLE001 — even the oracle failed
                logger.exception("frontier host re-verify errored")
                results = [False] * len(batch)
                errored = True
                if m is not None:
                    # One event under its own label: an infra error must
                    # not masquerade as a per-message signature attack.
                    m.frontier_verify_failures.labels(
                        msg_type="batch_error").inc()
        self.stats.batches += 1
        self.stats.max_batch = max(self.stats.max_batch, len(batch))
        now = time.perf_counter()
        for (_, _, _, fut, msg_type, t_enq, lane, crit), ok in zip(batch,
                                                                   results):
            lane._in_flight -= 1
            wait_s = now - t_enq
            if not ok:
                self.stats.failures += 1
                lane.tenant_stats.failures += 1
                if m is not None and not errored:
                    m.frontier_verify_failures.labels(
                        msg_type=msg_type).inc()
            lane.tenant_stats.record_wait(wait_s, crit)
            if m is not None:
                m.frontier_queue_wait_ms.observe(wait_s * 1000.0)
                m.frontier_tenant_queue_wait_ms.labels(
                    tenant=lane.tenant_id,
                    lane="critical" if crit else "gossip").observe(
                    wait_s * 1000.0)
            if not fut.done():
                fut.set_result(bool(ok))
