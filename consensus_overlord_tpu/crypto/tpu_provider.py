"""TpuBlsCrypto: the device-batched BLS12-381 crypto provider.

This is the component the reference could never have — its provider
(ophelia-blst → native blst, reference src/consensus.rs:336-337) verifies
one signature at a time on the CPU (src/consensus.rs:397-416) and loops
pair-by-pair to aggregate (src/consensus.rs:418-443).  Here the O(N) work
of a consensus round — N vote verifies at the leader, N pubkey
aggregations per QC check — is batched across TPU lanes:

* ``verify_batch``: random-linear-combination batch verification.  For
  signatures S_i on a common message hash H by pubkeys P_i, draw random
  64-bit r_i (blst's batch width; acceptance of a forged batch ≤ 2^-63
  per attempt, and the per-lane fallback then localizes) and check one
  relation
      e(Σ r_i·S_i, −g2) · e(H, Σ r_i·P_i) == 1
  The two multi-scalar-multiplications (the O(N) part) run on device as
  uniform double-and-add scans + a log₂(N) tree reduction; the two
  pairings (O(1)) run on the host oracle.  Distinct messages group into
  one extra pairing per distinct hash.  A failed batch falls back to
  per-signature verification, so results are exact, not probabilistic.

* ``aggregate_signatures`` / ``verify_aggregated_signature``: the QC
  hot path (reference src/consensus.rs:418-462) — device tree-sum over
  decompressed points for large N, host oracle below a crossover size.

Host↔device traffic is one transfer of packed int32 limb arrays each way
per batch — sized for a high-latency PJRT link where each dispatch is
expensive (SURVEY.md §7 hard part (c)).

Signing keys stay host-side (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import secrets
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compile_cache import enable as _enable_compile_cache
from ..core.sm3 import sm3_hash

# The provider's kernels are the big compiles; make sure every process
# that imports them shares the machine-wide persistent cache.
_enable_compile_cache()
from ..ops import bls12381_groups as dev
from ..ops.curve import Point
from . import bls12381 as oracle
from .provider import CpuBlsCrypto, CryptoError

# Batches are padded to the next size in this ladder so the number of
# distinct jit specializations stays small.
_PAD_SIZES = (8, 32, 128, 512, 1024, 2048, 8192)
# Random-linear-combination weight width.  64-bit weights (the width
# native blst uses for its batch verification) bound a forged batch's
# acceptance at 2^-64 per attempt; the per-lane fallback then localizes,
# so results stay exact.  Halves both MSM scan lengths vs 128-bit.
_SCALAR_BITS = 64


def _pad_to(n: int) -> int:
    for s in _PAD_SIZES:
        if n <= s:
            return s
    return -(-n // _PAD_SIZES[-1]) * _PAD_SIZES[-1]


# ---------------------------------------------------------------------------
# Device kernels (module-level so jax.jit caches by shape).
# ---------------------------------------------------------------------------

def g1_validate_msm_fn(x, sign, inf, ok, bits):
    """Decompress+validate a batch of G1 signatures and reduce Σ r_i·S_i.
    Returns (strict affine x, strict affine y, agg-is-infinity, per-lane
    valid).  Un-jitted core (per-lane subgroup-check variant, used by the
    multi-hash path; the single-hash fast path is verify_round_fn)."""
    pt, valid = dev.g1_decompress_device(x, sign, inf, ok)
    valid = valid & ~inf
    valid = valid & dev.g1_in_subgroup(pt)
    pt = dev.G1.select(valid, pt, dev.G1.infinity_like(x))
    agg = dev.G1.tree_sum(dev.G1.scalar_mul_bits(pt, bits))
    ax, ay, ainf = dev.G1.to_affine(agg)
    return dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0], valid


_g1_validate_msm = jax.jit(g1_validate_msm_fn)


def verify_round_fn(x, sign, inf, ok, bits, px, py, pz):
    """The fused single-dispatch consensus-round verification step — the
    flagship forward step.  One jit covers what used to be two kernel
    dispatches plus four canonicalization round-trips (each round-trip
    costs ~100 ms over a remote PJRT link, which dominated the measured
    batch time):

      G1: decompress + validate + per-lane fast subgroup check of the
        signatures, then Σ r_i·S_i
      G2: Σ r_i·P_i over the gathered pubkey rows, weights masked by the
        device-computed validity so both sides of the pairing relation
        see the same lane set

    The subgroup check must stay PER-LANE.  A batched-by-linearity form
    (check φ(A) == [λ]A on the aggregate only) is unsound: the G1
    cofactor is 3 · 11² · 10177² · …, so the per-lane residuals live in
    a group with small subgroups — a signature carrying the order-3
    point (0, 2) cancels out of the aggregate whenever its random weight
    is ≡ 0 (mod 3) (probability 1/3), and two colluding lanes cancel
    deterministically for ANY weight distribution.  A probabilistic
    accept of a non-subgroup signature that the host oracle rejects
    would split honest validators — consensus requires deterministic
    accept sets.  (tests/test_tpu_provider.py::TestSubgroupAttack pins
    both the random-cofactor and the order-3-component attacks.)

    Returns strict (numpy-decodable) affine coords for both aggregates
    plus the per-lane validity.
    """
    pt, valid = dev.g1_decompress_device(x, sign, inf, ok)
    valid = valid & ~inf & dev.g1_in_subgroup(pt)
    pt = dev.G1.select(valid, pt, dev.G1.infinity_like(x))
    agg = dev.G1.tree_sum(dev.G1.scalar_mul_bits(pt, bits))
    ax, ay, ainf = dev.G1.to_affine(agg)
    vbits = bits * valid[..., None].astype(bits.dtype)
    gagg = dev.G2.tree_sum(dev.G2.scalar_mul_bits(Point(px, py, pz), vbits))
    gx, gy, ginf = dev.G2.to_affine(gagg)
    return (dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0], valid,
            dev.FQ.strict(gx[0]), dev.FQ.strict(gy[0]), ginf[0])


_verify_round = jax.jit(verify_round_fn)


@jax.jit
def _g2_validate(x, sign, inf, ok):
    """Decompress + subgroup-check a batch of G2 public keys.  Returns
    projective coords + validity (used to fill the pubkey cache)."""
    pt, valid = dev.g2_decompress_device(x, sign, inf, ok)
    valid = valid & ~inf
    valid = valid & dev.g2_in_subgroup(pt)
    return pt.x, pt.y, pt.z, valid


@jax.jit
def _g2_msm(px, py, pz, bits):
    """Σ r_i·P_i over pre-validated G2 points; strict affine result."""
    agg = dev.G2.tree_sum(dev.G2.scalar_mul_bits(Point(px, py, pz), bits))
    ax, ay, ainf = dev.G2.to_affine(agg)
    return dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0]


@jax.jit
def _g1_validate_sum(x, sign, inf, ok):
    """Decompress a batch of G1 signatures and tree-sum them (the
    aggregation of reference src/consensus.rs:418-444).  No subgroup check,
    matching the oracle aggregate path."""
    pt, valid = dev.g1_decompress_device(x, sign, inf, ok)
    agg = dev.G1.tree_sum(
        dev.G1.select(valid & ~inf, pt, dev.G1.infinity_like(x)))
    ax, ay, ainf = dev.G1.to_affine(agg)
    return dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0], valid


@jax.jit
def _g2_sum(px, py, pz):
    """Σ P_i over pre-validated G2 points (QC pubkey aggregation,
    reference src/consensus.rs:365-383)."""
    agg = dev.G2.tree_sum(Point(px, py, pz))
    ax, ay, ainf = dev.G2.to_affine(agg)
    return dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0]


class _SingleChipKernels:
    """The module-level jits above, as the default kernel set."""

    g1_validate_msm = staticmethod(lambda *a: _g1_validate_msm(*a))
    g2_validate = staticmethod(lambda *a: _g2_validate(*a))
    g2_msm = staticmethod(lambda *a: _g2_msm(*a))
    g1_validate_sum = staticmethod(lambda *a: _g1_validate_sum(*a))
    g2_sum = staticmethod(lambda *a: _g2_sum(*a))
    verify_round = staticmethod(lambda *a: _verify_round(*a))
    lanes = 1


class _MeshKernels:
    """The same kernel surface jitted over a device mesh via shard_map
    (parallel/sharded.py): signature/pubkey lanes shard across devices,
    partial group sums combine over the mesh axis (ICI).  Batch padding
    must be a multiple of the mesh size; the provider's pad ladder is
    adjusted through `lanes`."""

    def __init__(self, mesh):
        from ..parallel import (
            sharded_g1_validate_sum,
            sharded_g1_verify_msm,
            sharded_g2_msm,
            sharded_g2_sum,
            sharded_g2_validate,
            sharded_verify_round,
        )
        self.mesh = mesh
        self.lanes = mesh.devices.size
        self.g1_validate_msm = sharded_g1_verify_msm(mesh)
        self.g2_validate = sharded_g2_validate(mesh)
        self.g2_msm = sharded_g2_msm(mesh)
        self.g1_validate_sum = sharded_g1_validate_sum(mesh)
        self.g2_sum = sharded_g2_sum(mesh)
        self.verify_round = sharded_verify_round(mesh)


def _affine_to_oracle_g1(ax, ay, ainf) -> Optional[Tuple[int, int]]:
    """Kernel outputs are strict — decode with numpy only (a device-side
    canonicalization here would cost an extra ~100 ms dispatch on a
    remote PJRT link)."""
    if bool(ainf):
        return None
    (xv,) = dev.FQ.ints_from_strict(np.asarray(ax))
    (yv,) = dev.FQ.ints_from_strict(np.asarray(ay))
    return (xv, yv)


def _affine_to_oracle_g2(ax, ay, ainf):
    if bool(ainf):
        return None
    xs = dev.FQ.ints_from_strict(np.asarray(ax))
    ys = dev.FQ.ints_from_strict(np.asarray(ay))
    return (tuple(xs), tuple(ys))


class TpuBlsCrypto:
    """CryptoProvider (reference Overlord `Crypto` trait surface,
    src/consensus.rs:385-463) with device-batched verification paths.

    `device_threshold`: below this batch size the host oracle is cheaper
    than a device round-trip (the PJRT link costs ~100 ms per dispatch);
    at or above it, work ships to the TPU.
    """

    def __init__(self, private_key: int, common_ref: bytes = b"",
                 device_threshold: int = 32, mesh=None):
        """mesh: optional jax.sharding.Mesh — batches then shard across its
        devices through the parallel/sharded.py kernels (single-chip jits
        otherwise).  Pass parallel.make_mesh() to use every local device."""
        self._cpu = CpuBlsCrypto(private_key, common_ref)
        self._common_ref = common_ref
        self._threshold = device_threshold
        self._kernels = (_MeshKernels(mesh) if mesh is not None
                         and mesh.devices.size > 1 else _SingleChipKernels)
        # Validated-pubkey cache, stacked for vectorized batch gathers
        # (a per-row Python loop costs ~0.5 s per 1024-lane batch):
        # voter bytes → row index into the stacked coord arrays, or -1
        # for known-bad keys.
        self._pk_index: Dict[bytes, int] = {}
        # Guards the cache arrays + index: the frontier's dispatch worker
        # and a service-thread reconfigure can race update_pubkeys, and an
        # interleaved base-capture/concatenate would desynchronize the
        # row offsets from the coordinate arrays.
        self._pk_lock = threading.Lock()
        self._pk_px = np.zeros((0, 2, dev.FQ.n), np.int32)
        self._pk_py = np.zeros((0, 2, dev.FQ.n), np.int32)
        self._pk_pz = np.zeros((0, 2, dev.FQ.n), np.int32)
        self._pk_aff: List[tuple] = []

    def _pad_to(self, n: int) -> int:
        """Pad ladder size, kept a multiple of the mesh lane count so
        shard_map can split the batch axis evenly."""
        size = _pad_to(n)
        lanes = self._kernels.lanes
        return -(-size // lanes) * lanes

    # -- provider surface ----------------------------------------------------

    @property
    def pub_key(self) -> bytes:
        return self._cpu.pub_key

    def hash(self, data: bytes) -> bytes:
        return sm3_hash(data)

    def sign(self, hash32: bytes) -> bytes:
        return self._cpu.sign(hash32)

    def verify_signature(self, signature: bytes, hash32: bytes,
                         voter: bytes) -> bool:
        return self._cpu.verify_signature(signature, hash32, voter)

    def aggregate_signatures(self, signatures: Sequence[bytes],
                             voters: Sequence[bytes]) -> bytes:
        if len(signatures) != len(voters):
            raise CryptoError(
                f"signatures x voters length mismatch "
                f"{len(signatures)} x {len(voters)}")
        if len(signatures) < self._threshold:
            return self._cpu.aggregate_signatures(signatures, voters)
        n = len(signatures)
        size = self._pad_to(n)
        parsed = dev.parse_g1_compressed(list(signatures))
        x = np.zeros((size, dev.FQ.n), np.int32)
        x[:n] = parsed.x
        sign_f = np.zeros(size, bool)
        sign_f[:n] = parsed.sign
        inf = np.zeros(size, bool)
        inf[:n] = parsed.infinity
        ok = np.zeros(size, bool)
        ok[:n] = parsed.wellformed
        # ONE device_get for the whole output tuple: each separate
        # np.asarray()/bool() on a device array is its own blocking D2H
        # round-trip (~150 ms on a remote PJRT link; five of them cost
        # more than the kernel).
        ax, ay, ainf, valid = jax.device_get(self._kernels.g1_validate_sum(
            jnp.asarray(x), jnp.asarray(sign_f), jnp.asarray(inf),
            jnp.asarray(ok)))
        if not bool(valid[:n].all()):
            raise CryptoError("invalid signature in aggregation batch")
        return oracle.g1_compress(_affine_to_oracle_g1(ax, ay, ainf))

    def verify_aggregated_signature(self, agg_sig: bytes, hash32: bytes,
                                    voters: Sequence[bytes]) -> bool:
        if len(voters) < self._threshold:
            return self._cpu.verify_aggregated_signature(
                agg_sig, hash32, voters)
        rows = self._pubkey_rows(voters)
        if rows is None:
            return False
        px, py, pz = rows
        agg_pk = _affine_to_oracle_g2(*jax.device_get(self._kernels.g2_sum(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(pz))))
        if agg_pk is None:
            return False
        try:
            sig_pt = oracle.g1_decompress(agg_sig)
        except ValueError:
            return False
        if sig_pt is None or not oracle.g1_in_subgroup(sig_pt):
            return False
        h = oracle.hash_to_g1(hash32, self._common_ref)
        neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
        return oracle.multi_pairing_is_one([(sig_pt, neg_g2), (h, agg_pk)])

    # -- batched verification ------------------------------------------------

    def verify_batch(self, signatures: Sequence[bytes],
                     hashes: Sequence[bytes],
                     voters: Sequence[bytes]) -> List[bool]:
        """Exact batched verification of (sig_i, hash_i, voter_i) triples.
        The common case — many votes on one hash — costs two device MSMs
        plus 1 + #distinct-hashes host pairings; a failed batch relation
        falls back to per-signature checks to localize the bad lanes."""
        n = len(signatures)
        assert len(hashes) == n and len(voters) == n
        if n == 0:
            return []
        if n < self._threshold:
            return [self._cpu.verify_signature(s, h, v)
                    for s, h, v in zip(signatures, hashes, voters)]

        (size, sx, ssign, sinf, sok, bits,
         pk_idx, pk_ok) = self._host_prep(signatures, voters, n)

        # Fast path — all lanes vote on ONE hash (the consensus common
        # case): a single fused dispatch computes both MSMs and the
        # per-lane validity (incl. subgroup checks).
        if len(set(map(bytes, hashes))) == 1:
            return self._dispatch_single_hash(
                signatures, bytes(hashes[0]), voters, n, size,
                sx, ssign, sinf, sok, bits, pk_idx, pk_ok)()

        ax, ay, ainf, valid = jax.device_get(self._kernels.g1_validate_msm(
            jnp.asarray(sx), jnp.asarray(ssign), jnp.asarray(sinf),
            jnp.asarray(sok), jnp.asarray(bits)))
        valid = valid[:n] & pk_ok
        agg_sig = _affine_to_oracle_g1(ax, ay, ainf)

        # Group lanes by message hash: one G2 MSM + one pairing per group.
        groups: Dict[bytes, List[int]] = {}
        for i, h in enumerate(hashes):
            if valid[i]:
                groups.setdefault(bytes(h), []).append(i)
        if not groups:
            return [False] * n

        neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
        pairs = [(agg_sig, neg_g2)]
        for h, idxs in groups.items():
            gsize = self._pad_to(len(idxs))
            rows = np.zeros(gsize, np.int64)
            rows[:len(idxs)] = pk_idx[idxs]
            px = self._pk_px[rows]
            py = self._pk_py[rows]
            pz = self._pk_pz[rows]
            px[len(idxs):] = 0
            py[len(idxs):] = 0
            pz[len(idxs):] = 0
            gbits = np.zeros((gsize, _SCALAR_BITS), np.int32)
            gbits[:len(idxs)] = bits[idxs]
            agg_pk = _affine_to_oracle_g2(*jax.device_get(
                self._kernels.g2_msm(
                    jnp.asarray(px), jnp.asarray(py), jnp.asarray(pz),
                    jnp.asarray(gbits))))
            h_pt = oracle.hash_to_g1(h, self._common_ref)
            pairs.append((h_pt, agg_pk))

        if oracle.multi_pairing_is_one(pairs):
            return list(valid)
        # Batch relation failed: localize with exact per-lane checks.
        return [bool(valid[i]) and self._verify_one_cached(
                    signatures[i], hashes[i], voters[i])
                for i in range(n)]

    def verify_batch_async(self, signatures: Sequence[bytes],
                           hashes: Sequence[bytes],
                           voters: Sequence[bytes]):
        """Pipelined form of verify_batch: dispatches the device work NOW
        and returns a zero-argument `resolve()` that blocks on the result
        and finishes host-side (pairing / fallback).

        The dispatch→readback round-trip on a remote PJRT link is ~200 ms
        regardless of batch size; issuing batch k+1 before resolving
        batch k overlaps that latency with device compute (measured 1.5x
        throughput at depth 4–8).  The engine's vote stream is exactly
        such a pipeline: the frontier can flush the next coalesced batch
        while the previous one's pairing finishes."""
        n = len(signatures)
        assert len(hashes) == n and len(voters) == n
        single = n > 0 and len(set(map(bytes, hashes))) == 1
        if n == 0 or n < self._threshold or not single:
            # Below-threshold and multi-hash batches take the sync path,
            # LAZILY: the frontier calls resolve() off the event loop, so
            # the blocking device work must happen there, not here.
            return lambda: self.verify_batch(signatures, hashes, voters)
        prep = self._host_prep(signatures, voters, n)
        return self._dispatch_single_hash(
            signatures, bytes(hashes[0]), voters, n, *prep[:6],
            prep[6], prep[7])

    # -- internals -----------------------------------------------------------

    def _host_prep(self, signatures, voters, n):
        """Shared host-side prep for BOTH the sync and async batch paths
        (one copy: the two paths must verify under identical parsing,
        padding, and RLC weight distributions or they drift apart):
        parse + pad signature fields, validate/cache pubkeys, draw
        weights.  Returns (size, sx, ssign, sinf, sok, bits, pk_idx,
        pk_ok)."""
        # Pubkeys: validate (cached) and gather device rows.
        pk_idx = self._pk_rows_of(voters)
        pk_ok = pk_idx >= 0
        size = self._pad_to(n)
        parsed = dev.parse_g1_compressed(list(signatures))
        sx = np.zeros((size, dev.FQ.n), np.int32)
        sx[:n] = parsed.x
        ssign = np.zeros(size, bool)
        ssign[:n] = parsed.sign
        sinf = np.zeros(size, bool)
        sinf[:n] = parsed.infinity
        sok = np.zeros(size, bool)
        # lanes with bad pubkeys are disabled entirely
        sok[:n] = parsed.wellformed & pk_ok
        # Random _SCALAR_BITS-wide weights (top bit forced: nonzero);
        # padding lanes get weight 0.  One vectorized unpackbits, not a
        # Python double loop (which costs ~100 ms per 1024-lane batch).
        packed = np.frombuffer(
            secrets.token_bytes(n * _SCALAR_BITS // 8),
            np.uint8).reshape(n, _SCALAR_BITS // 8).copy()
        packed[:, 0] |= 0x80  # force the top bit: scalars nonzero
        bits = np.zeros((size, _SCALAR_BITS), np.int32)
        bits[:n] = np.unpackbits(packed, axis=1)
        return size, sx, ssign, sinf, sok, bits, pk_idx, pk_ok

    def _dispatch_single_hash(self, signatures, h, voters, n, size,
                              sx, ssign, sinf, sok, bits, pk_idx, pk_ok):
        """Dispatch the fused kernel; return resolve() → List[bool]."""
        pad_rows = np.zeros(size, np.int64)
        pad_rows[:n] = np.maximum(pk_idx, 0)  # bad-key lanes: sok=False
        px = self._pk_px[pad_rows]
        py = self._pk_py[pad_rows]
        pz = self._pk_pz[pad_rows]
        out = self._kernels.verify_round(
            jnp.asarray(sx), jnp.asarray(ssign), jnp.asarray(sinf),
            jnp.asarray(sok), jnp.asarray(bits), jnp.asarray(px),
            jnp.asarray(py), jnp.asarray(pz))

        def resolve() -> List[bool]:
            # ONE device_get: separate per-output reads would each pay a
            # blocking D2H round-trip (~150 ms over a remote PJRT link) —
            # measured at 840 ms of the 1.1 s batch before this was fused.
            ax, ay, ainf, valid, gx, gy, ginf = jax.device_get(out)
            v = valid[:n] & pk_ok
            if not v.any():
                return [False] * n
            agg_sig = _affine_to_oracle_g1(ax, ay, ainf)
            agg_pk = _affine_to_oracle_g2(gx, gy, ginf)
            h_pt = oracle.hash_to_g1(h, self._common_ref)
            neg_g2 = (oracle.G2_GEN[0],
                      oracle.fq2_neg(oracle.G2_GEN[1]))
            if oracle.multi_pairing_is_one([(agg_sig, neg_g2),
                                            (h_pt, agg_pk)]):
                return list(v)
            # Batch relation failed: exact per-lane localization.
            return [bool(v[i]) and self._verify_one_cached(
                        signatures[i], h, voters[i])
                    for i in range(n)]

        return resolve

    def _verify_one_cached(self, sig: bytes, hash32: bytes,
                           voter: bytes) -> bool:
        row = self._pk_index.get(bytes(voter), -1)
        if row < 0:
            return False
        pk_aff = self._pk_aff[row]
        try:
            sig_pt = oracle.g1_decompress(sig)
        except ValueError:
            return False
        if sig_pt is None or not oracle.g1_in_subgroup(sig_pt):
            return False
        h = oracle.hash_to_g1(hash32, self._common_ref)
        neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
        return oracle.multi_pairing_is_one([(sig_pt, neg_g2), (h, pk_aff)])

    def _ensure_pubkeys(self, voters: Sequence[bytes]) -> None:
        missing = []
        seen = set()
        for v in voters:
            vb = bytes(v)
            if vb not in self._pk_index and vb not in seen:
                seen.add(vb)
                missing.append(vb)
        if not missing:
            return
        self.update_pubkeys(missing)

    def update_pubkeys(self, voters: Sequence[bytes]) -> None:
        """Validate and cache a validator set's public keys — the analog of
        the reference's pubkey cache refresh on reconfigure/commit
        (src/consensus.rs:131-136, 622-629), where a bad key is surfaced
        per-key instead of panicking."""
        voters = [bytes(v) for v in voters]
        with self._pk_lock:
            self._update_pubkeys_locked(voters)

    def _update_pubkeys_locked(self, voters: List[bytes]) -> None:
        voters = [v for v in voters if v not in self._pk_index]
        n = len(voters)
        if n == 0:
            return
        size = self._pad_to(n)
        parsed = dev.parse_g2_compressed(voters)
        x = np.zeros((size, 2, dev.FQ.n), np.int32)
        x[:n] = parsed.x
        sgn = np.zeros(size, bool)
        sgn[:n] = parsed.sign
        inf = np.zeros(size, bool)
        inf[:n] = parsed.infinity
        ok = np.zeros(size, bool)
        ok[:n] = parsed.wellformed
        px, py, pz, valid = jax.device_get(self._kernels.g2_validate(
            jnp.asarray(x), jnp.asarray(sgn), jnp.asarray(inf),
            jnp.asarray(ok)))
        aff = dev.g2_to_oracle(Point(jnp.asarray(px[:n]), jnp.asarray(py[:n]),
                                     jnp.asarray(pz[:n])))
        base = self._pk_px.shape[0]
        self._pk_px = np.concatenate([self._pk_px, px[:n]], axis=0)
        self._pk_py = np.concatenate([self._pk_py, py[:n]], axis=0)
        self._pk_pz = np.concatenate([self._pk_pz, pz[:n]], axis=0)
        self._pk_aff.extend(aff)
        for i, v in enumerate(voters):
            self._pk_index[v] = base + i if valid[i] else -1

    def _pk_rows_of(self, voters: Sequence[bytes]) -> Optional[np.ndarray]:
        """Row indices into the stacked pubkey arrays; None rows = -1."""
        self._ensure_pubkeys(voters)
        return np.fromiter((self._pk_index[bytes(v)] for v in voters),
                           np.int64, len(voters))

    def _pubkey_rows(self, voters: Sequence[bytes]):
        """Gathered, padded device rows for a voter list; None if any
        voter's key is invalid (an aggregated QC over a bad key can never
        verify)."""
        idx = self._pk_rows_of(voters)
        if (idx < 0).any():
            return None
        n = len(voters)
        size = self._pad_to(n)
        pad_idx = np.zeros(size, np.int64)
        pad_idx[:n] = idx
        px = self._pk_px[pad_idx]
        py = self._pk_py[pad_idx]
        pz = self._pk_pz[pad_idx]
        # padding lanes: projective identity (0:1:0)
        one2 = np.zeros((2, dev.FQ.n), np.int32)
        one2[0] = dev.FQ.from_int(1)
        px[n:] = 0
        py[n:] = one2
        pz[n:] = 0
        return px, py, pz
