"""TpuBlsCrypto: the device-batched BLS12-381 crypto provider.

This is the component the reference could never have — its provider
(ophelia-blst → native blst, reference src/consensus.rs:336-337) verifies
one signature at a time on the CPU (src/consensus.rs:397-416) and loops
pair-by-pair to aggregate (src/consensus.rs:418-443).  Here the O(N) work
of a consensus round — N vote verifies at the leader, N pubkey
aggregations per QC check — is batched across TPU lanes:

* ``verify_batch``: random-linear-combination batch verification.  For
  signatures S_i on message hashes H_g by pubkeys P_i, draw random
  64-bit r_i (blst's batch width; acceptance of a forged batch ≤ 2^-63
  per attempt, and the per-lane fallback then localizes) and check one
  relation
      e(Σ r_i·S_i, −g2) · Π_g e(H_g, Σ_{i∈g} r_i·P_i) == 1
  The multi-scalar-multiplications (the O(N) part) run on device as
  uniform windowed-ladder scans + tree reductions (ops/curve.py
  msm_bits — the formulation measured fastest on TPU; see the negative
  Pippenger result in its docstring); the pairings (O(1 + #distinct
  hashes)) run on the host native backend.  A failed batch falls back to
  per-signature verification, so results are exact, not probabilistic.

* ``aggregate_signatures`` / ``verify_aggregated_signature``: the QC
  hot path (reference src/consensus.rs:418-462) — device tree-sum over
  decompressed points for large N, host oracle below a crossover size.
  Both have ``*_async`` forms that dispatch device work immediately and
  return a blocking ``resolve()`` — the engine's event loop awaits the
  resolution off-thread (crypto/frontier.py) instead of stalling
  consensus timers on a device round-trip.

Host↔device traffic per batch is minimized for a high-latency PJRT link
(SURVEY.md §7 hard part (c)): the validated pubkey cache lives ON DEVICE
(uploaded once per reconfigure, gathered by row index inside the
kernel), and RLC weights ship packed as (B, 8) uint8 and unpack on
device — a batch uploads ~210 B/lane instead of ~1.2 KB/lane.

Signing keys stay host-side (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import logging
import os
import secrets
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compile_cache import enable as _enable_compile_cache
from ..core.sm3 import sm3_hash
from ..obs.fleet import current_round_id
from ..obs.prof import NULL_CALL, annotate
from .breaker import CircuitBreaker, DeviceLossError, DispatchTimeout

# The provider's kernels are the big compiles; make sure every process
# that imports them shares the machine-wide persistent cache.
_enable_compile_cache()
from ..ops import bls12381_groups as dev
from ..ops import pairing as pairing_ops
from ..ops.curve import Point
from . import bls12381 as oracle
from .provider import CpuBlsCrypto, CryptoError

logger = logging.getLogger("consensus_overlord_tpu.tpu_provider")

# Batches are padded to the next size in this ladder so the number of
# distinct jit specializations stays small.  4096 was missing through r4
# (a 4096-lane batch paid the 8192 kernel, 2x the MSM work); deployments
# that want fewer rungs pin the floor with CONSENSUS_PAD_MIN instead.
_PAD_SIZES = (8, 32, 128, 512, 1024, 2048, 4096, 8192)
# Random-linear-combination weight width.  64-bit weights (the width
# native blst uses for its batch verification) bound a forged batch's
# acceptance at 2^-64 per attempt; the per-lane fallback then localizes,
# so results stay exact.
_SCALAR_BITS = 64
# Pubkey-cache device capacity ladder (rows, kept replicated on every
# device): jit kernels specialize on the cache shape, so it grows in
# big steps and reuploads only on ladder crossings.
_PK_CAPS = (256, 1024, 4096, 16384)
# Fused multi-hash kernel group-count ladder: mixed vote+proposal+choke
# frontier batches carry ≤3 distinct hashes; k pads to one of these and
# larger hash counts split into pipelined single-hash sub-batches.
# k=3 has its own rung (r4): the common vote+proposal+choke mix was
# padding to 4 and paying a full G2 MSM for an always-empty group.
# Measured r5 (scripts/bench_k3_ab.py, interleaved A/B at N=8192,
# depth-8 pipeline): 11,501 vs 9,314 verifies/s median = 1.235x for
# 3-hash batches — the rung stays (BASELINE.md r5 ledger).
_GROUP_SIZES = (2, 3, 4)


def _pad_to(n: int) -> int:
    # CONSENSUS_PAD_MIN pins the bottom of the pad ladder: every batch
    # pads to at least this rung, so a deployment compiles ONE kernel
    # shape instead of one per rung the traffic happens to hit.  Worth
    # real money when cold compiles are expensive (a fresh rung through
    # the remote-compile relay can cost tens of minutes) and the rung's
    # runtime cost is flat (an 8-lane and a 32-lane batch cost the same
    # dispatch).
    floor = int(os.environ.get("CONSENSUS_PAD_MIN", "0"))
    for s in _PAD_SIZES:
        if n <= s and floor <= s:
            return s
    return -(-max(n, floor) // _PAD_SIZES[-1]) * _PAD_SIZES[-1]


def _pk_capacity(n: int) -> int:
    # CONSENSUS_PK_CAP_MIN pins the bottom of the capacity ladder, the
    # same economics as CONSENSUS_PAD_MIN: the device pubkey cache's row
    # capacity is part of every kernel's shape, so a deployment that
    # knows its fleet ceiling compiles ONE kernel set instead of one per
    # capacity rung its reconfigures happen to cross (16384 rows of G2
    # coords ≈ 15 MB of HBM — capacity is cheap, compiles are not).
    floor = int(os.environ.get("CONSENSUS_PK_CAP_MIN", "0"))
    n = max(n, floor)
    for s in _PK_CAPS:
        if n <= s:
            return s
    return -(-n // _PK_CAPS[-1]) * _PK_CAPS[-1]


# ---------------------------------------------------------------------------
# Device kernels (module-level so jax.jit caches by shape).
# ---------------------------------------------------------------------------

def verify_round_fn(x, sign, inf, ok, wpacked, rows, pkx, pky, pkz):
    """The fused single-dispatch consensus-round verification step — the
    flagship forward step.  One jit covers: weight unpack, G1 decompress
    + validate + per-lane fast subgroup check, the G1 MSM
    Σ r_i·S_i, the pubkey-cache gather, and the G2 MSM Σ r_i·P_i with
    weights masked by the device-computed validity so both sides of the
    pairing relation see the same lane set.  Returns strict
    (numpy-decodable) affine coords for both aggregates plus the
    per-lane validity — ONE device_get on the caller side (each extra
    D2H read costs ~150 ms over a remote PJRT link)."""
    bits = dev.unpack_weight_bits(wpacked)
    pt, valid = dev.g1_validate_batch(x, sign, inf, ok)
    agg = dev.G1.msm_bits(pt, bits)
    ax, ay, ainf = dev.G1.to_affine(agg)
    vbits = bits * valid[..., None].astype(bits.dtype)
    gagg = dev.G2.msm_bits(dev.gather_rows(rows, pkx, pky, pkz), vbits)
    gx, gy, ginf = dev.G2.to_affine(gagg)
    return (dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0], valid,
            dev.FQ.strict(gx[0]), dev.FQ.strict(gy[0]), ginf[0])


_verify_round = jax.jit(verify_round_fn)


def verify_round_multi_fn(x, sign, inf, ok, wpacked, rows, gmask,
                          pkx, pky, pkz):
    """k-hash fused verification round: one G1 MSM over all lanes plus
    one G2 MSM per hash group (weights masked by validity AND the
    host-computed group membership `gmask` (k, B)).  Mixed
    vote+proposal+choke frontier batches (≤4 distinct hashes) stay a
    single dispatch instead of degrading to serial per-group kernels.
    Returns G1 aggregate + validity + per-group G2 aggregates."""
    bits = dev.unpack_weight_bits(wpacked)
    pt, valid = dev.g1_validate_batch(x, sign, inf, ok)
    agg = dev.G1.msm_bits(pt, bits)
    ax, ay, ainf = dev.G1.to_affine(agg)
    pk = dev.gather_rows(rows, pkx, pky, pkz)
    outs = [dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0], valid]
    for g in range(gmask.shape[0]):
        m = valid & gmask[g]
        vbits = bits * m[..., None].astype(bits.dtype)
        gagg = dev.G2.msm_bits(pk, vbits)
        gx, gy, ginf = dev.G2.to_affine(gagg)
        outs += [dev.FQ.strict(gx[0]), dev.FQ.strict(gy[0]), ginf[0]]
    return tuple(outs)


_verify_round_multi = jax.jit(verify_round_multi_fn)

# Device multi-pairing pad ladder: a frontier flush pairs one signature
# aggregate with k hash groups (k ≤ _GROUP_SIZES[-1]), a QC check pairs
# exactly 2 — two rungs keep the pairing kernel at two compiled shapes.
_PAIR_SIZES = (2, 5)

#: −G2 generator — the constant Q of every verify relation's signature
#: pair e(Σ r_i·S_i, −g2): once as the host-oracle point tuple, once as
#: device limbs for the pairing kernel.
_NEG_G2_ORACLE = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
_NEG_G2_GEN_X = dev.FQ2.from_ints([_NEG_G2_ORACLE[0]])[0]
_NEG_G2_GEN_Y = dev.FQ2.from_ints([_NEG_G2_ORACLE[1]])[0]


# Device multi-pairing verdict: Π e(P_i, Q_i) == 1 over the pair axis
# with ONE shared final exponentiation — two staged dispatches
# (pair-rung-shaped Miller product + the rung-independent final-exp
# verdict kernel; see ops/pairing.py for the compile-cost rationale).
# This is the kernel pair that turns the `pairing` stage into a device
# number and shrinks the post-MSM readback to the verdict bitmap.
_multi_pairing = pairing_ops.multi_pairing_is_one_staged


def verify_round_tab_fn(x, sign, inf, ok, wpacked, rows, tx, ty, tz):
    """verify_round_fn with the G2 MSM served from PRECOMPUTED per-row
    window tables (ops/curve.py msm_from_tables) instead of the
    windowed ladder — the bench_g2_table_msm.py experiment promoted
    behind the g2_table_msm knob.  Tables are rebuilt per reconfigure
    (update_pubkeys), so the per-round path pays gathers + adds only."""
    bits = dev.unpack_weight_bits(wpacked)
    pt, valid = dev.g1_validate_batch(x, sign, inf, ok)
    agg = dev.G1.msm_bits(pt, bits)
    ax, ay, ainf = dev.G1.to_affine(agg)
    vbits = bits * valid[..., None].astype(bits.dtype)
    gagg = dev.G2.msm_from_tables(Point(tx, ty, tz), rows, vbits)
    gx, gy, ginf = dev.G2.to_affine(gagg)
    return (dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0], valid,
            dev.FQ.strict(gx[0]), dev.FQ.strict(gy[0]), ginf[0])


_verify_round_tab = jax.jit(verify_round_tab_fn)


def verify_round_multi_tab_fn(x, sign, inf, ok, wpacked, rows, gmask,
                              tx, ty, tz):
    """k-hash fused round with the per-group G2 MSMs from tables."""
    bits = dev.unpack_weight_bits(wpacked)
    pt, valid = dev.g1_validate_batch(x, sign, inf, ok)
    agg = dev.G1.msm_bits(pt, bits)
    ax, ay, ainf = dev.G1.to_affine(agg)
    tab = Point(tx, ty, tz)
    outs = [dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0], valid]
    for g in range(gmask.shape[0]):
        m = valid & gmask[g]
        vbits = bits * m[..., None].astype(bits.dtype)
        gagg = dev.G2.msm_from_tables(tab, rows, vbits)
        gx, gy, ginf = dev.G2.to_affine(gagg)
        outs += [dev.FQ.strict(gx[0]), dev.FQ.strict(gy[0]), ginf[0]]
    return tuple(outs)


_verify_round_multi_tab = jax.jit(verify_round_multi_tab_fn)


@jax.jit
def _build_g2_tables(px, py, pz):
    """Per-reconfigure G2 window-table build over the padded device
    pubkey cache (one 16-window × 16-digit multiple set per row)."""
    return dev.G2.msm_table_build(Point(px, py, pz))


@jax.jit
def _g2_validate(x, sign, inf, ok):
    """Decompress + subgroup-check a batch of G2 public keys.  Returns
    projective coords + validity (used to fill the pubkey cache)."""
    pt, valid = dev.g2_decompress_device(x, sign, inf, ok)
    valid = valid & ~inf
    valid = valid & dev.g2_in_subgroup(pt)
    return pt.x, pt.y, pt.z, valid


@jax.jit
def _g1_validate_sum(x, sign, inf, ok):
    """Decompress a batch of G1 signatures and tree-sum them (the
    aggregation of reference src/consensus.rs:418-444).  No subgroup check,
    matching the oracle aggregate path."""
    pt, valid = dev.g1_decompress_device(x, sign, inf, ok)
    agg = dev.G1.tree_sum(
        dev.G1.select(valid & ~inf, pt, dev.G1.infinity_like(x)))
    ax, ay, ainf = dev.G1.to_affine(agg)
    return dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0], valid


@jax.jit
def _g2_sum_rows(rows, mask, pkx, pky, pkz):
    """Σ P_i over cached pubkey rows (QC pubkey aggregation, reference
    src/consensus.rs:365-383) — padding lanes masked to the identity."""
    pk = dev.gather_rows(rows, pkx, pky, pkz)
    pk = dev.G2.select(mask, pk, dev.G2.infinity_like(pk.x))
    agg = dev.G2.tree_sum(pk)
    ax, ay, ainf = dev.G2.to_affine(agg)
    return dev.FQ.strict(ax[0]), dev.FQ.strict(ay[0]), ainf[0]


class _SingleChipKernels:
    """The module-level jits above, as the default kernel set."""

    g2_validate = staticmethod(lambda *a: _g2_validate(*a))
    g1_validate_sum = staticmethod(lambda *a: _g1_validate_sum(*a))
    g2_sum_rows = staticmethod(lambda *a: _g2_sum_rows(*a))
    verify_round = staticmethod(lambda *a: _verify_round(*a))
    verify_round_multi = staticmethod(lambda *a: _verify_round_multi(*a))
    verify_round_tab = staticmethod(lambda *a: _verify_round_tab(*a))
    verify_round_multi_tab = staticmethod(
        lambda *a: _verify_round_multi_tab(*a))
    build_g2_tables = staticmethod(lambda *a: _build_g2_tables(*a))
    multi_pairing = staticmethod(lambda *a: _multi_pairing(*a))
    #: Operand feed: single-chip inputs are plain device puts (the jit
    #: handles placement); the mesh set overrides with per-host shard
    #: feeding.  The axis_index arg mirrors _MeshKernels.ship.
    ship = staticmethod(lambda arr, axis_index=0: jnp.asarray(arr))
    ship_replicated = staticmethod(lambda arr: jnp.asarray(arr))
    lanes = 1


class _MeshKernels:
    """The same kernel surface jitted over a device mesh via shard_map
    (parallel/sharded.py): signature lanes and pubkey-row indices shard
    across devices, the pubkey cache is replicated, partial group sums
    combine over the mesh axis (ICI), and the pairing verdict runs as
    the sharded staged pair (per-device Miller partials, one all-gather
    of D Fq12 elements, one shared final exponentiation).  Batch
    padding must be a multiple of the mesh size; the provider's pad
    ladders (batch AND pair) are adjusted through `lanes`."""

    def __init__(self, mesh):
        from ..parallel import (
            host_shard_array,
            sharded_g1_validate_sum,
            sharded_g2_sum_rows,
            sharded_g2_validate,
            sharded_multi_pairing_is_one,
            sharded_verify_round,
            sharded_verify_round_multi,
        )
        self.mesh = mesh
        self.lanes = mesh.devices.size
        self._host_shard_array = host_shard_array
        self.g2_validate = sharded_g2_validate(mesh)
        self.g1_validate_sum = sharded_g1_validate_sum(mesh)
        self.g2_sum_rows = sharded_g2_sum_rows(mesh)
        self.verify_round = sharded_verify_round(mesh)
        self.verify_round_multi = sharded_verify_round_multi(mesh)
        self.multi_pairing = sharded_multi_pairing_is_one(mesh)

    def ship(self, arr, axis_index: int = 0):
        """Lanes-sharded operand feed: on a multi-process (DCN) mesh
        each host contributes its local lanes through
        jax.make_array_from_process_local_data, so a frontier flush is
        one mesh dispatch; single-process meshes are a plain device
        put.  axis_index picks which array axis carries the lanes
        (1 for the multi-hash gmask's (k, B) layout)."""
        if axis_index == 0:
            return self._host_shard_array(self.mesh, arr)
        from jax.sharding import PartitionSpec
        spec = PartitionSpec(*([None] * axis_index),
                             self.mesh.axis_names[0])
        return self._host_shard_array(self.mesh, arr, spec=spec)

    def ship_replicated(self, arr):
        """Host-identical operand feed (the replicated pubkey cache)."""
        return self._host_shard_array(self.mesh, arr, replicated=True)


def _affine_to_oracle_g1(ax, ay, ainf) -> Optional[Tuple[int, int]]:
    """Kernel outputs are strict — decode with numpy only (a device-side
    canonicalization here would cost an extra ~100 ms dispatch on a
    remote PJRT link)."""
    if bool(ainf):
        return None
    (xv,) = dev.FQ.ints_from_strict(np.asarray(ax))
    (yv,) = dev.FQ.ints_from_strict(np.asarray(ay))
    return (xv, yv)


def _affine_to_oracle_g2(ax, ay, ainf):
    if bool(ainf):
        return None
    xs = dev.FQ.ints_from_strict(np.asarray(ax))
    ys = dev.FQ.ints_from_strict(np.asarray(ay))
    return (tuple(xs), tuple(ys))


class TpuBlsCrypto:
    """CryptoProvider (reference Overlord `Crypto` trait surface,
    src/consensus.rs:385-463) with device-batched verification paths.

    `device_threshold`: below this batch size the host oracle is cheaper
    than a device round-trip (the PJRT link costs ~100 ms per dispatch);
    at or above it, work ships to the TPU.
    """

    def __init__(self, private_key: int, common_ref: bytes = b"",
                 device_threshold: int = 32, mesh=None,
                 qc_device_threshold: Optional[int] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 device_pairing: Optional[bool] = None,
                 g2_table_msm: Optional[bool] = None,
                 dispatch_deadline_s: Optional[float] = None):
        """mesh: optional jax.sharding.Mesh — batches then shard across its
        devices through the parallel/sharded.py kernels (single-chip jits
        otherwise).  Pass parallel.make_mesh() to use every local device.

        qc_device_threshold: separate device threshold for the QC paths
        (aggregate_signatures / verify_aggregated / pubkey validation);
        defaults to device_threshold.  The economics differ: a QC
        aggregate-verify costs the host ONE decompress + N point adds +
        2 pairings (~100 ms total), while N per-signature verifies cost
        ~100 ms EACH — so small fleets often want verifies on device
        but QC work on host (also: each path is its own kernel set, so
        splitting the thresholds halves the compile surface).

        breaker: device circuit breaker (crypto/breaker.py).  Every
        device path asks it before dispatching and reports outcomes; an
        open breaker routes everything to the host oracle, with periodic
        half-open probes back onto the device.  Pass your own to tune
        thresholds; the default trips after 3 consecutive device
        failures and probes every 5 s.

        device_pairing: run the Miller loop + shared final
        exponentiation ON DEVICE (ops/pairing.py) so the post-MSM
        readback shrinks to the verdict bitmap and the host oracle
        becomes the fallback/cross-check twin.  None (default) reads
        CONSENSUS_DEVICE_PAIRING (1/0/auto; auto = on for accelerator
        backends, off on the CPU lane where the host oracle is cheaper
        than the emulated tower).  Mesh providers run the sharded
        staged pair (parallel/sharded.py sharded_multi_pairing_is_one:
        per-device Miller partials over the pair shard, one all-gather
        of D Fq12 elements, the shared final exponentiation replicated)
        — the same breaker/fallback/cross-check semantics as one chip.

        g2_table_msm: serve the verify relation's G2 MSM from
        per-pubkey precomputed window tables rebuilt on reconfigure
        (ops/curve.py msm_table_build — the bench_g2_table_msm.py
        experiment promoted).  None reads CONSENSUS_G2_TABLE_MSM
        (default off: tables cost ~240 KB of HBM per cached pubkey
        row).  Single-chip kernels only.

        dispatch_deadline_s: watchdog deadline for each blocking device
        call (the readback end of a dispatch — JAX dispatch itself is
        asynchronous, so a wedged collective surfaces at device_get).
        Scaled by the batch rung (_deadline_for); a call that overruns
        becomes a DispatchTimeout breaker failure with an exact host
        re-verify instead of blocking the frontier worker forever.
        None reads CONSENSUS_DISPATCH_DEADLINE_S; <= 0 disables the
        watchdog (the pre-r18 unbounded behavior)."""
        self._cpu = CpuBlsCrypto(private_key, common_ref)
        self._common_ref = common_ref
        self._threshold = device_threshold
        self._qc_threshold = (qc_device_threshold
                              if qc_device_threshold is not None
                              else device_threshold)
        #: The configured full mesh (None = single-chip provider) — the
        #: ladder's top rung and the inventory sub-mesh rebuilds
        #: subtract quarantined lanes from.
        self._mesh = (mesh if mesh is not None
                      and mesh.devices.size > 1 else None)
        self._kernels = (_MeshKernels(self._mesh) if self._mesh is not None
                         else _SingleChipKernels)
        #: The full-rung kernel set, kept so stepping back up to
        #: full_mesh reuses the already-wrapped (and already-compiled)
        #: kernels instead of rebuilding them.
        self._full_kernels = self._kernels
        if dispatch_deadline_s is None:
            dispatch_deadline_s = float(os.environ.get(
                "CONSENSUS_DISPATCH_DEADLINE_S", "0"))
        #: Watchdog deadline base (see ctor docstring); <= 0 = off.
        self._dispatch_deadline_s = float(dispatch_deadline_s)
        #: Chaos hook (dcn_stall): monotonic timestamp until which every
        #: watched device call wedges — the fault the watchdog converts
        #: to a DispatchTimeout.  0.0 = clear.
        self._dcn_stall_until = 0.0
        #: Chaos hook (device_loss): {device_name: monotonic-until} —
        #: while armed, any dispatch whose CURRENT kernel set contains
        #: that lane raises DeviceLossError (carrying the lane name for
        #: supervisor quarantine).  A rebuilt sub-mesh that excludes the
        #: lane dispatches clean — exactly the self-healing contract.
        self._inject_loss: dict = {}
        #: Optional MeshSupervisor (parallel/supervisor.py): fed from
        #: _device_failed/_device_succeeded, consulted in
        #: _device_allowed, swaps kernel sets via apply_mesh_rung.
        self._supervisor = None
        single_chip = getattr(self._kernels, "mesh", None) is None
        if device_pairing is None:
            mode = os.environ.get("CONSENSUS_DEVICE_PAIRING", "auto")
            if mode == "auto":
                device_pairing = jax.default_backend() != "cpu"
            else:
                device_pairing = mode not in ("0", "off", "false")
        #: Device-resident pairing verdicts (see ctor docstring).  The
        #: host oracle remains the fallback twin behind the breaker.
        #: Mesh kernel sets carry their own sharded staged pair, so the
        #: knob alone decides — no single-chip gate (r14).
        self._pairing_on_device = bool(device_pairing)
        #: CONSENSUS_PAIRING_CROSSCHECK=1: every device verdict is also
        #: recomputed on the host oracle and mismatches are logged —
        #: the soak/debug twin mode (costs the full aggregate readback
        #: the device path otherwise skips).
        self._pairing_crosscheck = (
            os.environ.get("CONSENSUS_PAIRING_CROSSCHECK", "0") == "1")
        #: Host-oracle pairing calls taken where the device pairing was
        #: wanted but failed (dispatch/readback) — the acceptance gate:
        #: 0 on the happy path.  Plain int (single writer per resolve;
        #: mirrored into crypto_pairing_host_fallbacks_total when a
        #: registry is bound).
        self.pairing_host_fallbacks = 0
        if g2_table_msm is None:
            g2_table_msm = os.environ.get(
                "CONSENSUS_G2_TABLE_MSM", "0") not in ("0", "off", "false")
        self._use_g2_tables = bool(g2_table_msm) and single_chip
        #: Device-resident per-row G2 window tables (g2_table_msm);
        #: invalidated with _pk_dev on every cache append, rebuilt
        #: eagerly at the end of update_pubkeys (the reconfigure point).
        self._pk_tab: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None
        # Validated-pubkey cache, stacked for vectorized batch gathers
        # (a per-row Python loop costs ~0.5 s per 1024-lane batch):
        # voter bytes → row index into the stacked coord arrays, or -1
        # for known-bad keys.
        self._pk_index: Dict[bytes, int] = {}
        # Guards the cache arrays + index: the frontier's dispatch worker
        # and a service-thread reconfigure can race update_pubkeys, and an
        # interleaved base-capture/concatenate would desynchronize the
        # row offsets from the coordinate arrays.  RLock: a device
        # failure inside _update_pubkeys_locked can walk the supervisor
        # ladder down, and the resulting kernel swap (_swap_kernels)
        # must invalidate the device cache under this same lock.
        self._pk_lock = threading.RLock()
        self._pk_px = np.zeros((0, 2, dev.FQ.n), np.int32)
        self._pk_py = np.zeros((0, 2, dev.FQ.n), np.int32)
        self._pk_pz = np.zeros((0, 2, dev.FQ.n), np.int32)
        self._pk_aff: List[tuple] = []
        # Device-resident copy of the cache, padded to a capacity ladder
        # (stable kernel shapes).  Uploaded once per reconfigure — per
        # batch only the (B,) row indices travel over the link.
        self._pk_dev: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None
        #: Optional obs.Metrics: host-side phase timings for the device
        #: path (prep / readback / pairing) land in crypto_dispatch_ms.
        #: None (the default) keeps the measured bench path untouched.
        self.metrics = None
        #: Optional obs.prof.DeviceProfiler: staged per-call round
        #: profiles (parse/dispatch/readback/pairing into
        #: crypto_device_stage_seconds{stage,op} + the profile ring) and
        #: mesh-path gauges.  None = pre-profiling path.
        self.prof = None
        #: Cached collective-free twin of the mesh verify kernel
        #: (profile_sharded_stages probe) — built on first probe.
        self._stage_probe = None
        #: Chaos hook: {device_name: seconds} of synthetic delay added
        #: inside the per-device shard-fetch timing loop — the seeded
        #: fault injection the straggler detector's tests and the
        #: nightly fleet-obs lane use (inject_straggler()).  Empty in
        #: production.
        self._inject_straggler: dict = {}
        #: Device circuit breaker: consulted before every device
        #: dispatch, reported to after every resolve.  An open breaker
        #: means this provider is in degraded mode — exact results from
        #: the host oracle, no device traffic except half-open probes.
        self.breaker = breaker if breaker is not None else CircuitBreaker()

    def bind_metrics(self, metrics) -> None:
        """Attach a metric surface (obs.Metrics).  Observations run on
        the frontier's dispatch/resolver threads — prometheus_client is
        thread-safe, and every site is guarded so an unbound provider
        pays one attribute check."""
        self.metrics = metrics
        self.breaker.metrics = metrics

    def bind_profiler(self, prof) -> None:
        """Attach a device profiler (obs.prof.DeviceProfiler): every
        device op then records a staged per-call profile, and the mesh
        gauges (mesh_devices / device_kind) describe this provider's
        dispatch target."""
        self.prof = prof
        if prof is None:
            return
        mesh = getattr(self._kernels, "mesh", None)
        try:
            devices = (list(mesh.devices.flat) if mesh is not None
                       else jax.devices()[:1])
            prof.set_devices(devices)
        except Exception:  # noqa: BLE001 — profiling never breaks crypto
            pass

    def _prof_begin(self, op: str, n: int):
        """A StagedCall for one device op (the no-op twin when no
        profiler is bound, so call sites stay branch-free)."""
        return self.prof.begin(op, n) if self.prof is not None \
            else NULL_CALL

    def degraded_status(self) -> dict:
        """Breaker + fallback state for /statusz ("crypto" section)."""
        doc = self.breaker.status()
        doc["device_pairing"] = self._pairing_on_device
        doc["pairing_host_fallbacks"] = self.pairing_host_fallbacks
        doc["g2_table_msm"] = self._use_g2_tables
        return doc

    def _pairing_failed(self, exc: BaseException) -> None:
        """One device pairing dispatch/readback failure: feed the
        breaker like any device failure AND count the host-oracle
        pairing fallback (the r06 acceptance gate watches this stay 0
        on the happy path)."""
        self._device_failed("pairing", exc)
        self.pairing_host_fallbacks += 1
        if self.metrics is not None:
            self.metrics.pairing_host_fallbacks.inc()

    def _dispatch_pairing(self, g1s, g2s):
        """Dispatch the device multi-pairing verdict kernel over a
        flush's pairs.  g1s: [(x, y, inf)] G1 strict-limb coords ((n,)
        each, device or host); g2s: the matching [(x, y, inf)] Fq2
        coords ((2, n)).  Pads to the _PAIR_SIZES ladder, rounded up to
        a multiple of the kernel set's lane count — mesh pairing shards
        the pair axis across devices, and masked lanes contribute one —
        and returns the verdict device array — or None after feeding
        the breaker if the dispatch failed, so callers fall back to the
        host oracle twin."""
        try:
            self.breaker.raise_if_injected("pairing")
            k = len(g1s)
            size = next((s for s in _PAIR_SIZES if k <= s), k)
            lanes = self._kernels.lanes
            size = -(-size // lanes) * lanes
            z1 = jnp.zeros((dev.FQ.n,), jnp.int32)
            z2 = jnp.zeros((2, dev.FQ.n), jnp.int32)
            pad = size - k
            px = jnp.stack([jnp.asarray(g[0]) for g in g1s] + [z1] * pad)
            py = jnp.stack([jnp.asarray(g[1]) for g in g1s] + [z1] * pad)
            pinf = jnp.stack([jnp.asarray(g[2], bool) for g in g1s]
                             + [jnp.asarray(True)] * pad)
            qx = jnp.stack([jnp.asarray(g[0]) for g in g2s] + [z2] * pad)
            qy = jnp.stack([jnp.asarray(g[1]) for g in g2s] + [z2] * pad)
            qinf = jnp.stack([jnp.asarray(g[2], bool) for g in g2s]
                             + [jnp.asarray(True)] * pad)
            mask = np.arange(size) < k
            with annotate("tpu_bls.pairing.dispatch"):
                return self._kernels.multi_pairing(
                    px, py, pinf, qx, qy, qinf, self._kernels.ship(mask))
        except Exception as e:  # noqa: BLE001 — device pairing dispatch failed
            self._pairing_failed(e)
            return None

    @staticmethod
    def _h_limbs(h_pt):
        """Oracle G1 point → (x, y) strict limb arrays for the pairing
        kernel's hash-side pairs."""
        return dev.FQ.from_int(h_pt[0]), dev.FQ.from_int(h_pt[1])

    def _device_allowed(self, path: str) -> bool:
        """Ask the supervisor's ladder gate, then the breaker; count the
        fallback when routed to host.  On the host_oracle rung the
        supervisor says no while its probe cadence (record_success from
        the breaker's own half-open probes and small-batch host wins)
        steps the ladder back up."""
        sup = self._supervisor
        if sup is not None and not sup.allow_device():
            if self.metrics is not None:
                self.metrics.host_fallbacks.labels(path=path).inc()
            return False
        if self.breaker.allow():
            return True
        if self.metrics is not None:
            self.metrics.host_fallbacks.labels(path=path).inc()
        return False

    def _device_failed(self, path: str, exc: BaseException) -> None:
        """One device dispatch/readback failure: feed the breaker (and
        the mesh supervisor's ladder), count it, log it.  The caller
        then falls back to the host oracle."""
        logger.warning("device path %s failed (%s: %s); host fallback",
                       path, type(exc).__name__, exc)
        self.breaker.record_failure(f"{path}: {type(exc).__name__}")
        sup = self._supervisor
        if sup is not None:
            sup.record_failure(path, exc)
        if self.metrics is not None:
            self.metrics.device_failures.labels(path=path).inc()
            self.metrics.host_fallbacks.labels(path=path).inc()

    def _device_succeeded(self) -> None:
        """One clean device resolve: close the breaker loop AND feed the
        supervisor's step-up probe counter (real traffic is the probe)."""
        self.breaker.record_success()
        sup = self._supervisor
        if sup is not None:
            sup.record_success()

    # -- mesh resilience (watchdog + supervisor + chaos hooks) ---------------

    def attach_supervisor(self, supervisor) -> None:
        """Attach a MeshSupervisor (parallel/supervisor.py): from here on
        device outcomes walk its escalation ladder and apply_mesh_rung
        swaps this provider's kernel set on transitions."""
        self._supervisor = supervisor

    def mesh_device_names(self) -> List[str]:
        """The configured full-mesh lane inventory ("platform:id" names,
        matching the straggler detector's) — what sub-mesh rebuilds
        subtract quarantined lanes from.  Empty for single-chip
        providers (no sub_mesh rung exists)."""
        if self._mesh is None:
            return []
        return [f"{d.platform}:{d.id}" for d in self._mesh.devices.flat]

    def _current_lane_names(self) -> List[str]:
        """Lane names of the CURRENT kernel set (shrinks on sub-mesh
        rungs — a quarantined lost lane no longer blackholes dispatch)."""
        mesh = getattr(self._kernels, "mesh", None)
        if mesh is not None:
            return [f"{d.platform}:{d.id}" for d in mesh.devices.flat]
        try:
            d = jax.devices()[0]
        except Exception:  # noqa: BLE001 — backend gone: no lanes to name
            logger.warning("jax.devices() failed resolving lane names")
            return []
        return [f"{d.platform}:{d.id}"]

    def _lane_name(self, device) -> str:
        """Normalize a chaos target (lane index or "platform:id" name)
        against the full-mesh inventory."""
        names = self.mesh_device_names() or self._current_lane_names()
        if isinstance(device, int) or (isinstance(device, str)
                                       and device.isdigit()):
            return names[int(device) % len(names)] if names else str(device)
        return str(device)

    def inject_device_loss(self, device, seconds: float) -> None:
        """Chaos hook (sim `device_loss`): for `seconds`, any dispatch
        whose current kernel set contains `device` (lane index or
        "platform:id" name) raises DeviceLossError carrying the lane
        name — the supervisor quarantines it and rebuilds a survivor
        sub-mesh, after which dispatches run clean while the window is
        still live.  seconds <= 0 clears the lane."""
        name = self._lane_name(device)
        if seconds > 0:
            self._inject_loss[name] = time.monotonic() + float(seconds)
            logger.warning("device_loss armed: lane %s for %.2fs",
                           name, seconds)
        else:
            self._inject_loss.pop(name, None)

    def inject_dcn_stall(self, seconds: float) -> None:
        """Chaos hook (sim `dcn_stall`): for `seconds`, every watched
        device call wedges inside its dispatch window — the fault the
        watchdog converts to a DispatchTimeout within
        dispatch_deadline_s.  Compose with inject_straggler() to give
        the straggler detector the same degraded-lane signal.
        seconds <= 0 clears the window."""
        if seconds > 0:
            self._dcn_stall_until = time.monotonic() + float(seconds)
            logger.warning("dcn_stall armed for %.2fs", seconds)
        else:
            self._dcn_stall_until = 0.0

    def _dcn_stall_remaining(self) -> float:
        until = self._dcn_stall_until
        if until <= 0.0:
            return 0.0
        remaining = until - time.monotonic()
        if remaining <= 0.0:
            self._dcn_stall_until = 0.0
            return 0.0
        return remaining

    def _raise_if_lost(self, path: str) -> None:
        """Raise DeviceLossError when an armed lane loss targets a lane
        of the CURRENT kernel set (expired windows self-clear)."""
        if not self._inject_loss:
            return
        now = time.monotonic()
        current = None
        for name, until in list(self._inject_loss.items()):
            if now >= until:
                self._inject_loss.pop(name, None)
                continue
            if current is None:
                current = set(self._current_lane_names())
            if name in current:
                raise DeviceLossError(
                    name, f"{path}: injected loss of lane {name}")

    def _deadline_for(self, size: int) -> Optional[float]:
        """Watchdog deadline for one blocking device call, scaled by the
        batch rung: sqrt of the rung ratio — MSM work grows ~linearly
        with the rung, but fixed dispatch overhead dominates the small
        rungs, so linear scaling would let an 8192-lane deadline grow
        1024x.  None = watchdog off."""
        base = self._dispatch_deadline_s
        if base <= 0:
            return None
        return base * max(1.0, (max(int(size), 1) / _PAD_SIZES[0]) ** 0.5)

    def _watched(self, fn, *args, size: int = 0, path: str = "dispatch"):
        """Run one blocking device call (readback, or validate+readback)
        under the dispatch watchdog.  JAX dispatch is asynchronous, so a
        wedged collective surfaces at the blocking device_get — the
        chokepoint every device path funnels through.  Raises
        DeviceLossError while an injected lane loss targets the current
        kernel set and DispatchTimeout when the rung-scaled deadline
        expires; both flow through the caller's existing failure
        handling (breaker + supervisor + exact host fallback).  With the
        watchdog off this is a plain call (plus the chaos stall, which
        then wedges for real — the pre-r18 behavior under a wedged
        link)."""
        self._raise_if_lost(path)
        deadline = self._deadline_for(size)
        if deadline is None:
            stall = self._dcn_stall_remaining()
            if stall > 0.0:
                time.sleep(stall)
            return fn(*args)
        box: dict = {}
        done = threading.Event()

        def work():
            try:
                stall = self._dcn_stall_remaining()
                if stall > 0.0:
                    time.sleep(stall)  # the wedge the deadline cuts short
                box["result"] = fn(*args)
            # Not swallowed: the caller re-raises this on its own
            # thread right below (unless the deadline fired first, in
            # which case DispatchTimeout already took the failure path).
            except BaseException as e:  # noqa: BLE001
                box["error"] = e
            finally:
                done.set()

        # One daemon thread per watched call, not a pool: a wedged
        # device call holds its thread until the runtime returns, and a
        # pool's workers would leak away one wedge at a time until every
        # dispatch queued forever behind dead slots.
        t = threading.Thread(target=work, daemon=True,
                             name=f"dispatch-watchdog-{path}")
        t.start()
        if not done.wait(deadline):
            raise DispatchTimeout(
                f"{path}: device call exceeded dispatch deadline "
                f"{deadline:.2f}s (size={size})")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def apply_mesh_rung(self, rung: str, quarantined: Sequence[str]) -> None:
        """MeshSupervisor hook: swap the kernel set for a ladder rung.
        full_mesh reuses the ctor's kernel set; sub_mesh rebuilds
        _MeshKernels over the survivor devices (operands re-pad to the
        new lane multiple through self._kernels.lanes); single_chip is
        the module-jit set; host_oracle changes nothing here — the
        supervisor's allow_device() gate routes dispatch instead."""
        if rung == "host_oracle":
            return
        if rung == "full_mesh" and self._mesh is not None and not quarantined:
            kernels = self._full_kernels
        elif rung != "single_chip" and self._mesh is not None:
            from jax.sharding import Mesh
            dead = set(quarantined)
            survivors = [d for d in self._mesh.devices.flat
                         if f"{d.platform}:{d.id}" not in dead]
            if len(survivors) >= 2:
                kernels = _MeshKernels(
                    Mesh(np.asarray(survivors), self._mesh.axis_names))
            else:
                kernels = _SingleChipKernels
        else:
            kernels = _SingleChipKernels
        self._swap_kernels(kernels)
        logger.warning("mesh rung %s applied: %d lane(s)%s", rung,
                       kernels.lanes,
                       f", quarantined={sorted(quarantined)}"
                       if quarantined else "")

    def _swap_kernels(self, kernels) -> None:
        """Install a new kernel set and drop every mesh-resident cache
        placed on the old one (device pubkey copy, G2 tables, the stage
        probe's twins).  A dispatch racing the swap can mix old/new
        shapes and fail — that lands in the normal failure handling and
        re-verifies on the host, costing one batch of throughput, never
        correctness."""
        if kernels is self._kernels:
            return
        with self._pk_lock:
            self._kernels = kernels
            self._pk_dev = None
            self._pk_tab = None
        self._stage_probe = None

    #: crypto_dispatch_ms phase → crypto_device_stage_seconds stage (the
    #: stage family keeps profile_verify.py's names; "prep" has always
    #: been the parse/pad/RLC stage).
    _STAGE_OF = {"prep": "parse"}

    def _observe_phase(self, phase: str, t0: float, call=NULL_CALL) -> float:
        """Observe one host-side device-path phase (ms histogram + the
        staged call's stage record); returns a fresh timestamp so call
        sites can chain phases."""
        now = time.perf_counter()
        if self.metrics is not None:
            self.metrics.crypto_dispatch_ms.labels(phase=phase).observe(
                (now - t0) * 1000.0)
        call.observe(self._STAGE_OF.get(phase, phase), now - t0)
        return now

    def inject_straggler(self, device: str, seconds: float) -> None:
        """Chaos hook: add `seconds` of synthetic delay to `device`'s
        timed shard fetches (seconds <= 0 clears it).  The injected
        sleep sits INSIDE the per-device timing window, so the
        straggler detector sees exactly what a degraded D2H path would
        produce — the seeded fault the tests and the nightly
        fleet-obs-smoke lane assert on."""
        device = str(device)
        if seconds > 0:
            self._inject_straggler[device] = float(seconds)
        else:
            self._inject_straggler.pop(device, None)

    def _shard_latencies(self, sharded_out, sampled: bool = False,
                         stage: str = "readback") -> None:
        """Per-device fetch timing on a sharded output (the validity
        mask, sharded P(lanes)) AFTER the result is complete: with
        compute already drained, each shard's blocking fetch measures
        that device's D2H path alone, so a straggling or degraded chip
        is the outlier gauge.  Each fetch is still its own serialized
        D2H read (~150 ms over a remote PJRT link), so hot-path callers
        are THROTTLED through the profiler's sample interval — and run
        after the readback stage is observed, never inside it; only the
        explicit probe (profile_sharded_stages) passes sampled=True to
        bypass the throttle.  `stage` names the mesh stage this output
        attributes per device ('readback' on the hot path;
        'partial_reduce' / 'pairing_partial' from the probe's
        collective-free twins) — each sample lands in
        sharded_device_stage_seconds{device,stage} and the attached
        StragglerDetector via DeviceProfiler.device_stage."""
        if self.prof is None:
            return
        if not sampled:
            # Hot-path caller: only meaningful (and only throttled)
            # when the provider's own kernels run on a mesh.
            if getattr(self._kernels, "mesh", None) is None:
                return
            if not self.prof.want_device_sample():
                return
        try:
            round_id = current_round_id()
            device_stage = getattr(self.prof, "device_stage", None)
            for shard in sharded_out.addressable_shards:
                name = f"{shard.device.platform}:{shard.device.id}"
                delay = self._inject_straggler.get(name)
                t0 = time.perf_counter()
                if delay:
                    time.sleep(delay)
                np.asarray(shard.data)
                seconds = time.perf_counter() - t0
                if device_stage is not None:
                    device_stage(name, stage, seconds, round_id=round_id)
                else:  # pre-fleet profiler object: keep the r05 gauge
                    self.prof.device_latency(name, seconds)
        # graftlint: disable=CONC002 -- profiling-only D2H sample: the
        # real readback already succeeded and fed the breaker above;
        # a failed skew sample must never affect crypto results.
        except Exception:  # noqa: BLE001 — profiling never breaks crypto
            pass

    def _pad_to(self, n: int) -> int:
        """Pad ladder size, kept a multiple of the mesh lane count so
        shard_map can split the batch axis evenly."""
        size = _pad_to(n)
        lanes = self._kernels.lanes
        return -(-size // lanes) * lanes

    # -- provider surface ----------------------------------------------------

    @property
    def pub_key(self) -> bytes:
        return self._cpu.pub_key

    def hash(self, data: bytes) -> bytes:
        return sm3_hash(data)

    def sign(self, hash32: bytes) -> bytes:
        return self._cpu.sign(hash32)

    def verify_signature(self, signature: bytes, hash32: bytes,
                         voter: bytes) -> bool:
        return self._cpu.verify_signature(signature, hash32, voter)

    def aggregate_signatures(self, signatures: Sequence[bytes],
                             voters: Sequence[bytes]) -> bytes:
        return self.aggregate_signatures_async(signatures, voters)()

    def aggregate_signatures_async(self, signatures: Sequence[bytes],
                                   voters: Sequence[bytes]):
        """Dispatch the QC signature aggregation now; returns resolve()
        → compressed aggregate bytes (raises CryptoError on a bad lane).
        The engine's leader path awaits this off the event loop
        (crypto/frontier.py BatchingVerifier.aggregate)."""
        if len(signatures) != len(voters):
            raise CryptoError(
                f"signatures x voters length mismatch "
                f"{len(signatures)} x {len(voters)}")
        if (len(signatures) < self._qc_threshold
                or not self._device_allowed("aggregate")):
            return lambda: self._cpu.aggregate_signatures(signatures, voters)
        n = len(signatures)
        call = self._prof_begin("aggregate", n)
        try:
            self.breaker.raise_if_injected("aggregate")
            t0 = time.perf_counter()
            size = self._pad_to(n)
            call.pad(n, size)
            parsed = dev.parse_g1_compressed(list(signatures))
            x = np.zeros((size, dev.FQ.n), np.int32)
            x[:n] = parsed.x
            sign_f = np.zeros(size, bool)
            sign_f[:n] = parsed.sign
            inf = np.zeros(size, bool)
            inf[:n] = parsed.infinity
            ok = np.zeros(size, bool)
            ok[:n] = parsed.wellformed
            call.observe("parse", time.perf_counter() - t0)
            t0 = time.perf_counter()
            with annotate("tpu_bls.aggregate.dispatch"):
                ship = self._kernels.ship
                out = self._kernels.g1_validate_sum(
                    ship(x), ship(sign_f), ship(inf), ship(ok))
            call.observe("dispatch", time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — device dispatch failed
            self._device_failed("aggregate", e)
            call.finish(ok=False)
            return lambda: self._cpu.aggregate_signatures(signatures, voters)

        def resolve() -> bytes:
            # ONE device_get for the whole output tuple: each separate
            # np.asarray()/bool() on a device array is its own blocking
            # D2H round-trip (~150 ms on a remote PJRT link).
            t0 = time.perf_counter()
            try:
                ax, ay, ainf, valid = self._watched(
                    jax.device_get, out, size=size, path="aggregate")
            except Exception as e:  # noqa: BLE001 — device readback failed
                self._device_failed("aggregate", e)
                call.finish(ok=False)
                return self._cpu.aggregate_signatures(signatures, voters)
            self._device_succeeded()
            call.observe("readback", time.perf_counter() - t0)
            if not bool(valid[:n].all()):
                call.finish(ok=False)  # the call raised — never ring ok
                raise CryptoError("invalid signature in aggregation batch")
            call.finish()
            return oracle.g1_compress(_affine_to_oracle_g1(ax, ay, ainf))

        return resolve

    def verify_aggregated_signature(self, agg_sig: bytes, hash32: bytes,
                                    voters: Sequence[bytes]) -> bool:
        return self.verify_aggregated_async(agg_sig, hash32, voters)()

    def verify_aggregated_async(self, agg_sig: bytes, hash32: bytes,
                                voters: Sequence[bytes]):
        """Dispatch the QC pubkey aggregation now (device gather from the
        resident cache); returns resolve() → bool finishing host-side
        (signature decompress + 2 pairings)."""
        if (len(voters) < self._qc_threshold
                or not self._device_allowed("verify_aggregated")):
            return lambda: self._cpu.verify_aggregated_signature(
                agg_sig, hash32, voters)
        call = self._prof_begin("verify_aggregated", len(voters))
        try:
            self.breaker.raise_if_injected("verify_aggregated")
            t0 = time.perf_counter()
            idx = self._pk_rows_of(voters)
            if (idx < 0).any():
                # An aggregated QC over an invalid key can never verify
                # (no device dispatch happened: an ok=False record with
                # only the parse stage marks the early rejection).
                call.observe("parse", time.perf_counter() - t0)
                call.finish(ok=False)
                return lambda: False
            n = len(voters)
            size = self._pad_to(n)
            call.pad(n, size)
            rows = np.zeros(size, np.int64)
            rows[:n] = idx
            mask = np.zeros(size, bool)
            mask[:n] = True
            call.observe("parse", time.perf_counter() - t0)
            t0 = time.perf_counter()
            pkx, pky, pkz = self._pk_device()
            with annotate("tpu_bls.verify_aggregated.dispatch"):
                out = self._kernels.g2_sum_rows(
                    self._kernels.ship(rows), self._kernels.ship(mask),
                    pkx, pky, pkz)
            call.observe("dispatch", time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — device dispatch failed
            self._device_failed("verify_aggregated", e)
            call.finish(ok=False)
            return lambda: self._cpu.verify_aggregated_signature(
                agg_sig, hash32, voters)

        # Pipeline the verdict kernel right behind the pubkey sum (the
        # batch paths' shape): the signature decompress + hash map are
        # pure host work, so the pairing is in flight before resolve()
        # ever blocks on the link — not serialized behind an aggregate
        # readback.  An infinity aggregate skips its pair lane via
        # q_inf, leaving the product at e(sig, −g2) ≠ 1, so the verdict
        # agrees with the host path's "aggregate at infinity → False".
        verdict_dev = None
        sig_pt = None
        if self._pairing_on_device:
            try:
                sig_pt = oracle.g1_decompress(agg_sig)
            except ValueError:
                sig_pt = None
            if sig_pt is not None and not oracle.g1_in_subgroup(sig_pt):
                sig_pt = None  # same rejection the host path applies
            if sig_pt is not None:
                h_pt = oracle.hash_to_g1(hash32, self._common_ref)
                verdict_dev = self._dispatch_pairing(
                    [(dev.FQ.from_int(sig_pt[0]),
                      dev.FQ.from_int(sig_pt[1]), False),
                     (*self._h_limbs(h_pt), False)],
                    [(_NEG_G2_GEN_X, _NEG_G2_GEN_Y, False),
                     (out[0], out[1], out[2])])

        def resolve() -> bool:
            t0 = time.perf_counter()
            use_dev = self._pairing_on_device
            agg = None
            try:
                if use_dev and not self._pairing_crosscheck:
                    # Device-pairing path: only the infinity flag is
                    # read here; the aggregate stays on device for the
                    # pairing kernel.
                    ainf = bool(self._watched(jax.device_get, out[2],
                                              size=size,
                                              path="verify_aggregated"))
                else:
                    agg = self._watched(jax.device_get, out, size=size,
                                        path="verify_aggregated")
                    ainf = bool(agg[2])
            except Exception as e:  # noqa: BLE001 — device readback failed
                self._device_failed("verify_aggregated", e)
                call.finish(ok=False)
                return self._cpu.verify_aggregated_signature(
                    agg_sig, hash32, voters)
            self._device_succeeded()
            call.observe("readback", time.perf_counter() - t0)
            t0 = time.perf_counter()
            try:
                if ainf:
                    return False
                if sig_pt is None and use_dev:
                    # Decompress/subgroup already failed at dispatch.
                    return False
                if not use_dev:
                    try:
                        host_sig = oracle.g1_decompress(agg_sig)
                    except ValueError:
                        return False
                    if (host_sig is None
                            or not oracle.g1_in_subgroup(host_sig)):
                        return False
                else:
                    host_sig = sig_pt
                result = None
                if verdict_dev is not None:
                    try:
                        result = bool(self._watched(
                            jax.device_get, verdict_dev,
                            path="verify_aggregated"))
                    except Exception as e:  # noqa: BLE001 — readback
                        self._pairing_failed(e)
                        result = None
                if result is None:
                    # Host-oracle pairing twin (device pairing off, or
                    # its dispatch/readback failed above).
                    if agg is None:
                        try:
                            agg = self._watched(jax.device_get, out,
                                                size=size,
                                                path="verify_aggregated")
                        except Exception as e:  # noqa: BLE001 — readback
                            self._device_failed("verify_aggregated", e)
                            return self._cpu.verify_aggregated_signature(
                                agg_sig, hash32, voters)
                    agg_pk = _affine_to_oracle_g2(*agg)
                    if agg_pk is None:
                        return False
                    h = oracle.hash_to_g1(hash32, self._common_ref)
                    result = oracle.multi_pairing_is_one(
                        [(host_sig, _NEG_G2_ORACLE), (h, agg_pk)])
                elif self._pairing_crosscheck and agg is not None:
                    agg_pk = _affine_to_oracle_g2(*agg)
                    h = oracle.hash_to_g1(hash32, self._common_ref)
                    host_r = (False if agg_pk is None else
                              oracle.multi_pairing_is_one(
                                  [(host_sig, _NEG_G2_ORACLE),
                                   (h, agg_pk)]))
                    if host_r != result:
                        logger.error(
                            "device pairing verdict %s != host oracle %s "
                            "(verify_aggregated, %d voters)", result,
                            host_r, len(voters))
                # Observed only when the pairing actually ran: garbage
                # QCs returning early above must not flood the stage
                # with near-zero samples and collapse its percentiles.
                call.observe("pairing", time.perf_counter() - t0)
                return result
            finally:
                call.finish()

        return resolve

    # -- batched verification ------------------------------------------------

    def verify_batch(self, signatures: Sequence[bytes],
                     hashes: Sequence[bytes],
                     voters: Sequence[bytes]) -> List[bool]:
        """Exact batched verification of (sig_i, hash_i, voter_i) triples.
        The common case — many votes on one hash — costs two device MSMs
        plus 1 + #distinct-hashes host pairings; a failed batch relation
        falls back to per-signature checks to localize the bad lanes."""
        return self.verify_batch_async(signatures, hashes, voters)()

    def verify_batch_async(self, signatures: Sequence[bytes],
                           hashes: Sequence[bytes],
                           voters: Sequence[bytes]):
        """Pipelined form of verify_batch: dispatches the device work NOW
        and returns a zero-argument `resolve()` that blocks on the result
        and finishes host-side (pairing / fallback).

        The dispatch→readback round-trip on a remote PJRT link is ~200 ms
        regardless of batch size; issuing batch k+1 before resolving
        batch k overlaps that latency with device compute (measured 1.5x
        throughput at depth 4–8).  The engine's vote stream is exactly
        such a pipeline: the frontier can flush the next coalesced batch
        while the previous one's pairing finishes.

        Every ≥threshold batch dispatches immediately — single-hash and
        ≤4-hash batches as ONE fused kernel, larger hash counts as
        per-hash single-hash sub-batches issued back-to-back (still
        pipelined; nothing silently degrades to a blocking path)."""
        n = len(signatures)
        assert len(hashes) == n and len(voters) == n
        if n == 0:
            return lambda: []
        if n < self._threshold or not self._device_allowed("verify_batch"):
            # Host-oracle path — no device dispatch to pipeline; resolve
            # lazily so the frontier's off-loop worker pays the CPU cost.
            return lambda: [self._cpu.verify_signature(s, h, v)
                            for s, h, v in zip(signatures, hashes, voters)]

        groups: Dict[bytes, List[int]] = {}
        for i, h in enumerate(hashes):
            groups.setdefault(bytes(h), []).append(i)

        # Created before any failure point (incl. the injected-fault
        # raise) so the except below finishes the real record — every
        # failed device attempt lands in the ring as ok=False.  The
        # >ladder split below never rings it (each sub-batch profiles
        # itself); an empty unfinished call has no side effects.
        call = self._prof_begin("verify_batch", n)
        try:
            self.breaker.raise_if_injected("verify_batch")
            if len(groups) == 1:
                t0 = time.perf_counter()
                prep = self._host_prep(signatures, voters, n, call=call)
                self._observe_phase("prep", t0, call)
                return self._dispatch_single_hash(
                    signatures, bytes(hashes[0]), voters, n, *prep,
                    call=call)
            if len(groups) <= _GROUP_SIZES[-1]:
                return self._dispatch_multi_hash(signatures, voters, n,
                                                 groups, call=call)
        except Exception as e:  # noqa: BLE001 — device dispatch failed
            self._device_failed("verify_batch", e)
            call.finish(ok=False)
            return lambda: [self._cpu.verify_signature(s, h, v)
                            for s, h, v in zip(signatures, hashes, voters)]
        # Many distinct hashes (beyond the fused-kernel ladder): verify
        # each hash group as its own single-hash sub-batch, dispatched
        # back-to-back now and resolved together.
        resolvers = []
        for h, idxs in groups.items():
            resolvers.append((idxs, self.verify_batch_async(
                [signatures[i] for i in idxs], [h] * len(idxs),
                [voters[i] for i in idxs])))

        def resolve_split() -> List[bool]:
            results = [False] * n
            for idxs, r in resolvers:
                for i, ok in zip(idxs, r()):
                    results[i] = ok
            return results

        return resolve_split

    # -- internals -----------------------------------------------------------

    def _host_prep(self, signatures, voters, n, call=NULL_CALL):
        """Shared host-side prep for every batch path (one copy: all
        paths must verify under identical parsing, padding, and RLC
        weight distributions or they drift apart): parse + pad signature
        fields, validate/cache pubkeys, draw packed weights.  Returns
        (size, sx, ssign, sinf, sok, wpacked, rows, pk_idx, pk_ok)."""
        # Pubkeys: validate (cached) and resolve device cache rows.
        pk_idx = self._pk_rows_of(voters)
        pk_ok = pk_idx >= 0
        size = self._pad_to(n)
        call.pad(n, size)
        if self.metrics is not None:
            # Padded-rung occupancy, observed where the pad is computed:
            # every device batch — fused single/multi-hash AND each
            # sub-batch of a >ladder split (which recurses through
            # verify_batch_async back into here) — reports exactly the
            # lanes it ships; host-path batches never reach this.
            self.metrics.frontier_occupancy.observe(n / size)
            if size > n:
                self.metrics.frontier_padded_lanes.inc(size - n)
        parsed = dev.parse_g1_compressed(list(signatures))
        sx = np.zeros((size, dev.FQ.n), np.int32)
        sx[:n] = parsed.x
        ssign = np.zeros(size, bool)
        ssign[:n] = parsed.sign
        sinf = np.zeros(size, bool)
        sinf[:n] = parsed.infinity
        sok = np.zeros(size, bool)
        # lanes with bad pubkeys are disabled entirely
        sok[:n] = parsed.wellformed & pk_ok
        # Random 64-bit weights, packed big-endian (top bit forced:
        # nonzero); padding lanes get weight 0.  Unpacked on device —
        # 8 B/lane over the link instead of 256.
        wpacked = np.zeros((size, _SCALAR_BITS // 8), np.uint8)
        wpacked[:n] = np.frombuffer(
            secrets.token_bytes(n * _SCALAR_BITS // 8),
            np.uint8).reshape(n, _SCALAR_BITS // 8)
        wpacked[:n, 0] |= 0x80  # force the top bit: scalars nonzero
        rows = np.zeros(size, np.int64)
        rows[:n] = np.maximum(pk_idx, 0)  # bad-key lanes: sok=False
        return size, sx, ssign, sinf, sok, wpacked, rows, pk_idx, pk_ok

    def _dispatch_single_hash(self, signatures, h, voters, n, size,
                              sx, ssign, sinf, sok, wpacked, rows,
                              pk_idx, pk_ok, call=NULL_CALL):
        """Dispatch the fused kernel (plus, when device pairing is on,
        the multi-pairing verdict kernel pipelined right behind it);
        return resolve() → List[bool]."""
        t0 = time.perf_counter()
        ship = self._kernels.ship
        if self._use_g2_tables:
            tx, ty, tz = self._pk_tables()
            with annotate("tpu_bls.verify_round.dispatch"):
                out = self._kernels.verify_round_tab(
                    ship(sx), ship(ssign), ship(sinf),
                    ship(sok), ship(wpacked), ship(rows), tx, ty, tz)
        else:
            pkx, pky, pkz = self._pk_device()
            with annotate("tpu_bls.verify_round.dispatch"):
                out = self._kernels.verify_round(
                    ship(sx), ship(ssign), ship(sinf),
                    ship(sok), ship(wpacked), ship(rows), pkx, pky, pkz)
        self._observe_phase("dispatch", t0, call)
        verdict_dev = None
        if self._pairing_on_device:
            # The verdict is on device before resolve() runs; only the
            # validity bitmap + one bool cross the link afterwards.
            verdict_dev = self._dispatch_pairing(
                [(out[0], out[1], out[2]),
                 (*self._h_limbs(oracle.hash_to_g1(h, self._common_ref)),
                  False)],
                [(_NEG_G2_GEN_X, _NEG_G2_GEN_Y, False),
                 (out[4], out[5], out[6])])

        def resolve() -> List[bool]:
            # ONE device_get: separate per-output reads would each pay a
            # blocking D2H round-trip (~150 ms over a remote PJRT link) —
            # measured at 840 ms of the 1.1 s batch before this was fused.
            # On the device-pairing path only the validity bitmap is
            # fetched; the aggregates stay on device.
            t0 = time.perf_counter()
            slim = verdict_dev is not None and not self._pairing_crosscheck
            ax = ay = ainf = gx = gy = ginf = None
            try:
                if slim:
                    valid = self._watched(jax.device_get, out[3],
                                          size=size, path="verify_batch")
                else:
                    ax, ay, ainf, valid, gx, gy, ginf = self._watched(
                        jax.device_get, out, size=size, path="verify_batch")
            except Exception as e:  # noqa: BLE001 — device readback failed
                self._device_failed("verify_batch", e)
                call.finish(ok=False)
                return [self._cpu.verify_signature(signatures[i], h,
                                                   voters[i])
                        for i in range(n)]
            self._device_succeeded()
            self._observe_phase("readback", t0, call)
            # Per-chip skew sample AFTER the readback stage is observed
            # (compute drained): its extra D2H reads must never inflate
            # or hollow out ANY stage histogram (throttled) — t0 is
            # re-taken below so the pairing stage excludes it too.
            self._shard_latencies(out[3])
            t0 = time.perf_counter()
            try:
                v = valid[:n] & pk_ok
                if not v.any():
                    return [False] * n
                paired = None
                if verdict_dev is not None:
                    try:
                        paired = bool(self._watched(
                            jax.device_get, verdict_dev,
                            path="verify_batch"))
                        self._observe_phase("pairing", t0, call)
                    except Exception as e:  # noqa: BLE001 — pairing readback
                        self._pairing_failed(e)
                        paired = None
                if paired is None:
                    # Host-oracle pairing twin: the only path when device
                    # pairing is off, the exact fallback when it failed.
                    if ax is None:
                        try:
                            (ax, ay, ainf, _, gx, gy,
                             ginf) = self._watched(
                                 jax.device_get, out, size=size,
                                 path="verify_batch")
                        except Exception as e:  # noqa: BLE001 — readback
                            self._device_failed("verify_batch", e)
                            return [bool(v[i]) and self._verify_one_cached(
                                        signatures[i], h, voters[i])
                                    for i in range(n)]
                    paired = self._host_pairing_single(ax, ay, ainf,
                                                       gx, gy, ginf, h)
                    self._observe_phase("pairing", t0, call)
                elif self._pairing_crosscheck:
                    host_p = self._host_pairing_single(ax, ay, ainf,
                                                       gx, gy, ginf, h)
                    if host_p != paired:
                        logger.error(
                            "device pairing verdict %s != host oracle %s "
                            "(single-hash batch n=%d)", paired, host_p, n)
                if paired:
                    return list(v)
                # Batch relation failed: exact per-lane localization.
                return [bool(v[i]) and self._verify_one_cached(
                            signatures[i], h, voters[i])
                        for i in range(n)]
            finally:
                call.finish()

        return resolve

    def _host_pairing_single(self, ax, ay, ainf, gx, gy, ginf, h) -> bool:
        """The host-oracle pairing tail of a single-hash batch — the
        pre-r06 mandatory last hop, now the fallback/cross-check twin."""
        agg_sig = _affine_to_oracle_g1(ax, ay, ainf)
        agg_pk = _affine_to_oracle_g2(gx, gy, ginf)
        h_pt = oracle.hash_to_g1(h, self._common_ref)
        return oracle.multi_pairing_is_one([(agg_sig, _NEG_G2_ORACLE),
                                            (h_pt, agg_pk)])

    def _dispatch_multi_hash(self, signatures, voters, n,
                             groups: Dict[bytes, List[int]],
                             call=NULL_CALL):
        """Dispatch the k-group fused kernel (k padded up the group-count
        ladder with empty masks); return resolve() → List[bool]."""
        t0 = time.perf_counter()
        (size, sx, ssign, sinf, sok, wpacked, rows,
         pk_idx, pk_ok) = self._host_prep(signatures, voters, n, call=call)
        k = next(s for s in _GROUP_SIZES if len(groups) <= s)
        gmask = np.zeros((k, size), bool)
        ghashes = list(groups)
        for g, h in enumerate(ghashes):
            gmask[g, groups[h]] = True
        t0 = self._observe_phase("prep", t0, call)
        ship = self._kernels.ship
        if self._use_g2_tables:
            tx, ty, tz = self._pk_tables()
            with annotate("tpu_bls.verify_round_multi.dispatch"):
                out = self._kernels.verify_round_multi_tab(
                    ship(sx), ship(ssign), ship(sinf),
                    ship(sok), ship(wpacked), ship(rows),
                    ship(gmask, axis_index=1), tx, ty, tz)
        else:
            pkx, pky, pkz = self._pk_device()
            with annotate("tpu_bls.verify_round_multi.dispatch"):
                out = self._kernels.verify_round_multi(
                    ship(sx), ship(ssign), ship(sinf),
                    ship(sok), ship(wpacked), ship(rows),
                    ship(gmask, axis_index=1), pkx, pky, pkz)
        self._observe_phase("dispatch", t0, call)
        lane_hashes = self._lane_hashes(groups, n)
        verdict_dev = None
        if self._pairing_on_device:
            # One pair per hash group + the signature pair, one shared
            # final exponentiation on device.  Groups whose aggregate
            # lands at infinity (no valid lane voted on that hash) are
            # skipped by the kernel's q_inf mask — the exact analog of
            # the host path's "nothing to pair" continue.
            g1s = [(out[0], out[1], out[2])]
            g2s = [(_NEG_G2_GEN_X, _NEG_G2_GEN_Y, False)]
            for g, h in enumerate(ghashes):
                h_pt = oracle.hash_to_g1(h, self._common_ref)
                g1s.append((*self._h_limbs(h_pt), False))
                g2s.append(tuple(out[4 + 3 * g: 7 + 3 * g]))
            verdict_dev = self._dispatch_pairing(g1s, g2s)

        def resolve() -> List[bool]:
            t0 = time.perf_counter()
            slim = verdict_dev is not None and not self._pairing_crosscheck
            flat = None
            try:
                if slim:
                    valid = self._watched(jax.device_get, out[3],
                                          size=size, path="verify_batch")
                else:
                    flat = self._watched(jax.device_get, out,
                                         size=size, path="verify_batch")
                    valid = flat[3]
            except Exception as e:  # noqa: BLE001 — device readback failed
                self._device_failed("verify_batch", e)
                call.finish(ok=False)
                return [self._cpu.verify_signature(signatures[i],
                                                   lane_hashes[i], voters[i])
                        for i in range(n)]
            self._device_succeeded()
            self._observe_phase("readback", t0, call)
            self._shard_latencies(out[3])  # post-readback skew sample
            t0 = time.perf_counter()  # pairing excludes the sample's D2H
            try:
                v = valid[:n] & pk_ok
                if not v.any():
                    return [False] * n
                paired = None
                if verdict_dev is not None:
                    try:
                        paired = bool(self._watched(
                            jax.device_get, verdict_dev,
                            path="verify_batch"))
                        self._observe_phase("pairing", t0, call)
                    except Exception as e:  # noqa: BLE001 — pairing readback
                        self._pairing_failed(e)
                        paired = None
                if paired is None:
                    if flat is None:
                        try:
                            flat = self._watched(jax.device_get, out,
                                                 size=size,
                                                 path="verify_batch")
                        except Exception as e:  # noqa: BLE001 — readback
                            self._device_failed("verify_batch", e)
                            return [bool(v[i]) and self._verify_one_cached(
                                        signatures[i], lane_hashes[i],
                                        voters[i])
                                    for i in range(n)]
                    paired = self._host_pairing_multi(flat, ghashes)
                    self._observe_phase("pairing", t0, call)
                elif self._pairing_crosscheck:
                    host_p = self._host_pairing_multi(flat, ghashes)
                    if host_p != paired:
                        logger.error(
                            "device pairing verdict %s != host oracle %s "
                            "(%d-hash batch n=%d)", paired, host_p,
                            len(ghashes), n)
                if paired:
                    return list(v)
                # Batch relation failed: exact per-lane localization.
                return [bool(v[i]) and self._verify_one_cached(
                            signatures[i], lane_hashes[i], voters[i])
                        for i in range(n)]
            finally:
                call.finish()

        return resolve

    def _host_pairing_multi(self, flat, ghashes) -> bool:
        """Host-oracle pairing tail of a k-hash batch (fallback/cross-
        check twin of the device multi-pairing)."""
        ax, ay, ainf = flat[:3]
        agg_sig = _affine_to_oracle_g1(ax, ay, ainf)
        pairs = [(agg_sig, _NEG_G2_ORACLE)]
        for g, h in enumerate(ghashes):
            gx, gy, ginf = flat[4 + 3 * g: 7 + 3 * g]
            agg_pk = _affine_to_oracle_g2(gx, gy, ginf)
            if agg_pk is None:
                # No valid lane voted on this hash — nothing to pair.
                continue
            pairs.append((oracle.hash_to_g1(h, self._common_ref), agg_pk))
        return oracle.multi_pairing_is_one(pairs)

    def profile_sharded_stages(self, signatures, voters,
                               warm: bool = True) -> dict:
        """Sampled mesh probe: split the fused verify round into its
        per-device local stage vs its cross-device combine stage, which
        one fused program cannot expose.  Times (block_until_ready) the
        collective-free twin (sharded_verify_round_local: validate +
        partial MSMs, outputs sharded) and the full kernel; the
        difference is the all-gather over ICI + the replicated log2(D)
        finish.  Observes sharded_partial_reduce_seconds /
        sharded_allgather_seconds and per-device shard-fetch latency
        through the bound profiler; returns the timings.

        The pairing stage gets the same split (r14): the collective-free
        Miller twin (sharded_miller_partial_local — per-device Miller
        loops + local tree product, output still sharded) vs the full
        Miller-product kernel (all_gather of the D Fq12 partials + the
        replicated combine tree); the difference is the pairing combine.
        The shared final exponentiation is deliberately excluded — it is
        replicated and shape-independent, and its cost already shows in
        the verify_batch/pairing stage histogram.  Observes
        sharded_pairing_partial_seconds / sharded_pairing_combine_seconds
        on a generator-pair fixture (one pair per lane; only stage
        timing matters, not the verdict).

        COSTS real dispatches (plus a one-time compile of the twins on
        `warm`), so it runs where sampling is explicit —
        scripts/profile_verify.py and ProfileSession captures — never
        on the per-batch hot path.  Works on a 1-device mesh too (the
        combine stage then measures all_gather's single-device cost)."""
        from ..parallel import (
            make_mesh,
            sharded_miller_partial_local,
            sharded_miller_product,
            sharded_verify_round,
            sharded_verify_round_local,
        )

        n = len(signatures)
        mesh = getattr(self._kernels, "mesh", None)
        if self._stage_probe is None:
            if mesh is None:
                mesh = make_mesh()  # every local device; 1 is fine
            self._stage_probe = (sharded_verify_round_local(mesh),
                                 sharded_verify_round(mesh),
                                 sharded_miller_partial_local(mesh),
                                 sharded_miller_product(mesh), mesh)
        (local_fn, full_fn, pair_local_fn, pair_full_fn,
         mesh) = self._stage_probe
        lanes = mesh.devices.size
        # Metrics detached around prep: the probe's synthetic batch must
        # not pollute frontier_batch_occupancy / frontier_padded_lanes,
        # which report what actually ships through the frontier.  (The
        # probe is an explicit offline sample, never concurrent with a
        # hot-path flush on the same provider.)
        metrics, self.metrics = self.metrics, None
        try:
            (size, sx, ssign, sinf, sok, wpacked, rows,
             pk_idx, pk_ok) = self._host_prep(signatures, voters, n)
        finally:
            self.metrics = metrics
        if size % lanes:  # provider padded for its own kernels' lanes
            pad = -(-size // lanes) * lanes
            sx = np.concatenate([sx, np.zeros((pad - size, dev.FQ.n),
                                              np.int32)])
            ssign, sinf, sok, rows, wpacked = (
                np.concatenate([a, np.zeros((pad - size,) + a.shape[1:],
                                            a.dtype)])
                for a in (ssign, sinf, sok, rows, wpacked))
            size = pad
        args = (jnp.asarray(sx), jnp.asarray(ssign), jnp.asarray(sinf),
                jnp.asarray(sok), jnp.asarray(wpacked), jnp.asarray(rows),
                *self._pk_device())
        if warm:  # first touch is the compile, not the stage
            jax.block_until_ready(local_fn(*args))
            jax.block_until_ready(full_fn(*args))
        t0 = time.perf_counter()
        with annotate("tpu_bls.probe.partial_reduce"):
            local_out = local_fn(*args)
            jax.block_until_ready(local_out)
        t_local = time.perf_counter() - t0
        t0 = time.perf_counter()
        with annotate("tpu_bls.probe.full_round"):
            jax.block_until_ready(full_fn(*args))
        t_full = time.perf_counter() - t0
        t_combine = max(t_full - t_local, 0.0)
        # Pairing split on a generator-pair fixture: e(G1, −G2) per lane,
        # every lane live — representative Miller work, verdict unused.
        pair_args = (
            jnp.asarray(np.tile(np.asarray(dev.FQ.from_int(
                oracle.G1_GEN[0])), (lanes, 1))),
            jnp.asarray(np.tile(np.asarray(dev.FQ.from_int(
                oracle.G1_GEN[1])), (lanes, 1))),
            jnp.zeros(lanes, bool),
            jnp.asarray(np.tile(np.asarray(_NEG_G2_GEN_X), (lanes, 1, 1))),
            jnp.asarray(np.tile(np.asarray(_NEG_G2_GEN_Y), (lanes, 1, 1))),
            jnp.zeros(lanes, bool),
            jnp.ones(lanes, bool),
        )
        if warm:
            jax.block_until_ready(pair_local_fn(*pair_args))
            jax.block_until_ready(pair_full_fn(*pair_args))
        t0 = time.perf_counter()
        with annotate("tpu_bls.probe.pairing_partial"):
            pair_local_out = pair_local_fn(*pair_args)
            jax.block_until_ready(pair_local_out)
        t_pair_local = time.perf_counter() - t0
        t0 = time.perf_counter()
        with annotate("tpu_bls.probe.pairing_full"):
            jax.block_until_ready(pair_full_fn(*pair_args))
        t_pair_full = time.perf_counter() - t0
        t_pair_combine = max(t_pair_full - t_pair_local, 0.0)
        device_stage_s = None
        if self.prof is not None:
            self.prof.sharded("partial_reduce", t_local)
            self.prof.sharded("allgather", t_combine)
            self.prof.sharded("pairing_partial", t_pair_local)
            self.prof.sharded("pairing_combine", t_pair_combine)
            # Per-device attribution: the twins' outputs are still
            # sharded, so each stage gets its own shard-fetch pass
            # (plus the hot path's readback rows already recorded).
            self._shard_latencies(local_out[2], sampled=True,
                                  stage="partial_reduce")
            self._shard_latencies(pair_local_out, sampled=True,
                                  stage="pairing_partial")
            totals = getattr(self.prof, "device_stage_totals", None)
            if totals is not None:
                device_stage_s = totals()
        return {"devices": int(lanes), "batch": n, "padded": int(size),
                "partial_reduce_s": t_local, "allgather_s": t_combine,
                "pairing_partial_s": t_pair_local,
                "pairing_combine_s": t_pair_combine,
                "pairing_full_s": t_pair_full,
                "full_s": t_full,
                "device_stage_s": device_stage_s}

    @staticmethod
    def _lane_hashes(groups: Dict[bytes, List[int]], n: int) -> List[bytes]:
        lane = [b""] * n
        for h, idxs in groups.items():
            for i in idxs:
                lane[i] = h
        return lane

    def _verify_one_cached(self, sig: bytes, hash32: bytes,
                           voter: bytes) -> bool:
        row = self._pk_index.get(bytes(voter), -1)
        if row < 0:
            return False
        pk_aff = self._pk_aff[row]
        try:
            sig_pt = oracle.g1_decompress(sig)
        except ValueError:
            return False
        if sig_pt is None or not oracle.g1_in_subgroup(sig_pt):
            return False
        h = oracle.hash_to_g1(hash32, self._common_ref)
        return oracle.multi_pairing_is_one([(sig_pt, _NEG_G2_ORACLE),
                                            (h, pk_aff)])

    def _ensure_pubkeys(self, voters: Sequence[bytes]) -> None:
        missing = []
        seen = set()
        for v in voters:
            vb = bytes(v)
            if vb not in self._pk_index and vb not in seen:
                seen.add(vb)
                missing.append(vb)
        if not missing:
            return
        self.update_pubkeys(missing)

    def update_pubkeys(self, voters: Sequence[bytes]) -> None:
        """Validate and cache a validator set's public keys — the analog of
        the reference's pubkey cache refresh on reconfigure/commit
        (src/consensus.rs:131-136, 622-629), where a bad key is surfaced
        per-key instead of panicking."""
        voters = [bytes(v) for v in voters]
        with self._pk_lock:
            self._update_pubkeys_locked(voters)
        if self._use_g2_tables:
            try:
                # Rebuild the G2 window tables HERE, at the reconfigure
                # point, so the first post-reconfigure verify pays
                # gathers only.  A failed build stays lazy: the verify
                # paths retry it inside their breaker-guarded dispatch.
                self._pk_tables()
            except Exception as e:  # noqa: BLE001 — device build failed
                self._device_failed("update_pubkeys", e)

    def _update_pubkeys_locked(self, voters: List[bytes]) -> None:
        voters = [v for v in voters if v not in self._pk_index]
        n = len(voters)
        if n == 0:
            return
        if (n < self._qc_threshold
                or not self._device_allowed("update_pubkeys")):
            # Small reconfigure (e.g. a 4-validator net): host validation
            # is cheaper than a device dispatch round-trip — the same
            # threshold economics as the QC paths.  Also the degraded
            # route when the breaker has the device fenced off.
            self._update_pubkeys_host(voters)
            return
        try:
            self.breaker.raise_if_injected("update_pubkeys")
            size = self._pad_to(n)
            parsed = dev.parse_g2_compressed(voters)
            x = np.zeros((size, 2, dev.FQ.n), np.int32)
            x[:n] = parsed.x
            sgn = np.zeros(size, bool)
            sgn[:n] = parsed.sign
            inf = np.zeros(size, bool)
            inf[:n] = parsed.infinity
            ok = np.zeros(size, bool)
            ok[:n] = parsed.wellformed
            ship = self._kernels.ship
            px, py, pz, valid = self._watched(
                jax.device_get,
                self._kernels.g2_validate(ship(x), ship(sgn),
                                          ship(inf), ship(ok)),
                size=size, path="update_pubkeys")
            aff = dev.g2_to_oracle(Point(jnp.asarray(px[:n]),
                                         jnp.asarray(py[:n]),
                                         jnp.asarray(pz[:n])))
        except Exception as e:  # noqa: BLE001 — device validation failed
            self._device_failed("update_pubkeys", e)
            self._update_pubkeys_host(voters)
            return
        self._device_succeeded()
        self._append_pk_rows(voters, px[:n], py[:n], pz[:n], aff, valid)

    def _append_pk_rows(self, voters: List[bytes], px, py, pz,
                        aff: List, valid) -> None:
        """The single cache-append tail both validation paths share: host-
        and device-validated rows MUST enter the stacked arrays / affine
        list / index identically or batch gathers desynchronize."""
        base = self._pk_px.shape[0]
        self._pk_px = np.concatenate([self._pk_px, px], axis=0)
        self._pk_py = np.concatenate([self._pk_py, py], axis=0)
        self._pk_pz = np.concatenate([self._pk_pz, pz], axis=0)
        self._pk_aff.extend(aff)
        for i, v in enumerate(voters):
            self._pk_index[v] = base + i if valid[i] else -1
        self._pk_dev = None  # device copy is stale; re-upload lazily
        self._pk_tab = None  # window tables too (g2_table_msm)

    def _update_pubkeys_host(self, voters: List[bytes]) -> None:
        """Host-oracle twin of the device validation path: decompress +
        subgroup-check each key on the CPU and append its limb-encoded
        affine form (z = 1) to the same stacked cache arrays, so batch
        kernels gather host- and device-validated rows identically."""
        n = len(voters)
        px = np.zeros((n, 2, dev.FQ.n), np.int32)
        py = np.zeros((n, 2, dev.FQ.n), np.int32)
        pz = np.zeros((n, 2, dev.FQ.n), np.int32)
        aff: List[tuple] = []
        valid = np.zeros(n, bool)
        for i, v in enumerate(voters):
            try:
                pt = oracle.g2_decompress(v)
            except ValueError:
                pt = None
            if pt is None or not oracle.g2_in_subgroup(pt):
                aff.append(None)
                continue
            (x0, x1), (y0, y1) = pt
            px[i, 0] = dev.FQ.from_int(x0)
            px[i, 1] = dev.FQ.from_int(x1)
            py[i, 0] = dev.FQ.from_int(y0)
            py[i, 1] = dev.FQ.from_int(y1)
            pz[i, 0] = dev.FQ.from_int(1)
            valid[i] = True
            aff.append(pt)
        self._append_pk_rows(voters, px, py, pz, aff, valid)

    def _pk_device(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """The device-resident pubkey cache, padded to the capacity
        ladder (stable kernel shapes).  Re-uploaded only after
        update_pubkeys grew the host arrays — a per-reconfigure cost;
        per batch only the (B,) row indices travel over the link."""
        with self._pk_lock:
            return self._pk_device_locked()

    def _pk_device_locked(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Body of _pk_device — caller holds self._pk_lock."""
        if self._pk_dev is None:
            rows = max(self._pk_px.shape[0], 1)
            cap = _pk_capacity(rows)
            px = np.zeros((cap, 2, dev.FQ.n), np.int32)
            py = np.zeros((cap, 2, dev.FQ.n), np.int32)
            pz = np.zeros((cap, 2, dev.FQ.n), np.int32)
            px[:self._pk_px.shape[0]] = self._pk_px
            py[:self._pk_py.shape[0]] = self._pk_py
            pz[:self._pk_pz.shape[0]] = self._pk_pz
            ship_r = self._kernels.ship_replicated
            self._pk_dev = (ship_r(px), ship_r(py), ship_r(pz))
        return self._pk_dev

    def _pk_tables(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Device-resident G2 window tables over the padded pubkey
        cache (g2_table_msm).  Rebuilt only after update_pubkeys grew
        the host arrays — a per-reconfigure cost, like _pk_device's
        upload, but ~256x the HBM (16 windows × 16 digits per row).
        The device fetch and the staleness check share ONE critical
        section: fetching outside the lock would let a concurrent
        update_pubkeys invalidate both caches between the two steps and
        this thread then cache tables built from the pre-reconfigure
        upload as fresh."""
        with self._pk_lock:
            if self._pk_tab is None:
                px, py, pz = self._pk_device_locked()
                tab = self._kernels.build_g2_tables(px, py, pz)
                self._pk_tab = (tab.x, tab.y, tab.z)
            return self._pk_tab

    def _pk_rows_of(self, voters: Sequence[bytes]) -> np.ndarray:
        """Row indices into the stacked pubkey arrays; bad keys = -1."""
        self._ensure_pubkeys(voters)
        return np.fromiter((self._pk_index[bytes(v)] for v in voters),
                           np.int64, len(voters))
