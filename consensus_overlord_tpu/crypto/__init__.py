"""Crypto port and backends: CPU oracle (BLS12-381, Ed25519) and TPU-batched
providers (limb-field arithmetic under jit, Pallas kernels)."""

from .provider import (  # noqa: F401
    CpuBlsCrypto,
    CryptoError,
    CryptoProvider,
    Ed25519Crypto,
    load_private_key,
)
