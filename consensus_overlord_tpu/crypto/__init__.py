"""Crypto port and backends: CPU oracles (BLS12-381, Ed25519, secp256k1,
SM2) and TPU-batched providers (limb-field arithmetic under jit).

Device-batched providers live in their own modules so importing this
package stays cheap: tpu_provider (BLS), ed25519_tpu, ecdsa_tpu
(secp256k1 + SM2)."""

from .provider import (  # noqa: F401
    CpuBlsCrypto,
    CryptoError,
    CryptoProvider,
    Ed25519Crypto,
    load_private_key,
)
