"""ctypes bridge to the native BLS12-381 backend (csrc/bls381.c).

The shared object is built on demand with the system compiler (the build
environment ships g++/cc but no pybind11; ctypes keeps the binding layer
dependency-free).  If no compiler is available the import still succeeds
with ``AVAILABLE = False`` and callers fall back to the pure-Python oracle
— the native path is an accelerator, never a requirement.

Layout conventions (must match csrc/bls381.c):
  Fp          6 x u64 little-endian canonical limbs
  G1 affine   12 u64 (x, y);  all-zero = point at infinity
  G2 affine   24 u64 (x.c0, x.c1, y.c0, y.c1); all-zero = infinity
  Fp12        72 u64, (c0.a0.c0, c0.a0.c1, c0.a1.c0, ... c1.a2.c1)
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Iterable, List, Optional, Sequence, Tuple

logger = logging.getLogger("consensus_overlord_tpu.native")

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO_PATH = os.path.join(_CSRC, "_bls381.so")
_SRC_PATH = os.path.join(_CSRC, "bls381.c")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False  # memoized: never retry a failed build per process
AVAILABLE = False


def _build() -> bool:
    if not os.path.exists(_SRC_PATH):
        return False
    src_mtime = os.path.getmtime(_SRC_PATH)
    if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= src_mtime:
        return True
    # Compile to a temp path and rename into place: concurrent processes
    # sharing a checkout must never dlopen a half-written .so.
    tmp_path = f"{_SO_PATH}.{os.getpid()}.tmp"
    for cc in ("cc", "gcc", "g++", "clang"):
        try:
            subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-o", tmp_path, _SRC_PATH],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, _SO_PATH)
            return True
        except (FileNotFoundError, subprocess.CalledProcessError,
                subprocess.TimeoutExpired, OSError) as e:
            logger.debug("native build with %s failed: %s", cc, e)
    try:
        os.unlink(tmp_path)
    except OSError:
        pass
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed, AVAILABLE
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not _build():
            _load_failed = True
            logger.info("native BLS backend unavailable; "
                        "using the pure-Python pairing path")
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:  # pragma: no cover
            logger.warning("native BLS backend failed to load: %s", e)
            _load_failed = True
            return None
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.bls381_multi_pairing_is_one.restype = ctypes.c_int
        lib.bls381_multi_pairing_is_one.argtypes = [u64p, u64p,
                                                    ctypes.c_int32]
        for name in ("bls381_pairing", "bls381_miller"):
            fn = getattr(lib, name)
            fn.restype = None
            fn.argtypes = [u64p, u64p, u64p]
        lib.bls381_final_exp.restype = None
        lib.bls381_final_exp.argtypes = [u64p, u64p]
        _lib = lib
        AVAILABLE = True
        return lib


def _fp_limbs(v: int) -> List[int]:
    return [(v >> (64 * i)) & 0xFFFFFFFFFFFFFFFF for i in range(6)]


def _limbs_to_int(limbs: Sequence[int]) -> int:
    out = 0
    for i, l in enumerate(limbs):
        out |= int(l) << (64 * i)
    return out


def _pack_g1(pt) -> List[int]:
    if pt is None:
        return [0] * 12
    x, y = pt
    return _fp_limbs(x) + _fp_limbs(y)


def _pack_g2(pt) -> List[int]:
    if pt is None:
        return [0] * 24
    (x0, x1), (y0, y1) = pt
    return _fp_limbs(x0) + _fp_limbs(x1) + _fp_limbs(y0) + _fp_limbs(y1)


def available() -> bool:
    return _load() is not None


def multi_pairing_is_one(pairs: Iterable[Tuple[object, object]]) -> bool:
    """Native Π e(P_i, Q_i) == 1 over oracle-format affine points
    (ints for G1, int-pairs for G2; None = infinity).  Raises
    RuntimeError if the backend is unavailable — call available() first
    or use crypto.backend which handles the fallback."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native BLS backend unavailable")
    g1s: List[int] = []
    g2s: List[int] = []
    k = 0
    for p, q in pairs:
        g1s.extend(_pack_g1(p))
        g2s.extend(_pack_g2(q))
        k += 1
    if k == 0:
        return True
    a1 = (ctypes.c_uint64 * len(g1s))(*g1s)
    a2 = (ctypes.c_uint64 * len(g2s))(*g2s)
    return bool(lib.bls381_multi_pairing_is_one(a1, a2, k))


def _fp12_out_to_tuple(out) -> tuple:
    vals = [_limbs_to_int(out[i * 6:(i + 1) * 6]) for i in range(12)]
    def fq2(i):
        return (vals[i], vals[i + 1])
    return (((fq2(0)), (fq2(2)), (fq2(4))), ((fq2(6)), (fq2(8)), (fq2(10))))


def pairing(p, q) -> tuple:
    """e(P, Q)^3 (the oracle's cubed convention) as an oracle-format Fq12
    tuple — used by the cross-validation tests."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native BLS backend unavailable")
    a1 = (ctypes.c_uint64 * 12)(*_pack_g1(p))
    a2 = (ctypes.c_uint64 * 24)(*_pack_g2(q))
    out = (ctypes.c_uint64 * 72)()
    lib.bls381_pairing(a1, a2, out)
    return _fp12_out_to_tuple(list(out))


def miller(p, q) -> tuple:
    lib = _load()
    if lib is None:
        raise RuntimeError("native BLS backend unavailable")
    a1 = (ctypes.c_uint64 * 12)(*_pack_g1(p))
    a2 = (ctypes.c_uint64 * 24)(*_pack_g2(q))
    out = (ctypes.c_uint64 * 72)()
    lib.bls381_miller(a1, a2, out)
    return _fp12_out_to_tuple(list(out))
