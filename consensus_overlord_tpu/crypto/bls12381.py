"""BLS12-381 pairing-based signatures — pure-Python CPU oracle.

The reference's crypto provider is BLS12-381 via ophelia-blst (native blst
C/assembly; reference src/consensus.rs:336-337, 385-463): min-sig layout with
48-byte G1 signatures and 96-byte G2 public keys that double as validator
addresses (src/consensus.rs:352-357, 406).  This module is a from-scratch
implementation of the full stack — Fq/Fq2/Fq6/Fq12 tower, curve arithmetic,
optimal-ate pairing, ZCash-format point (de)compression, hash-to-G1, and the
sign / verify / aggregate / aggregate-verify surface — used as the
correctness oracle for the batched TPU backends in crypto/fields.py and
crypto/kernels/.

Scheme (min-sig, mirroring blst's BLS_SIG_BASIC on G1):
    sk ∈ Z_r;   pk = sk·G2  (96B compressed);   sig = sk·H(m) ∈ G1 (48B)
    verify:      e(sig, G2gen) == e(H(m), pk)
    agg-verify:  e(agg_sig, G2gen) == e(H(m), Σ pk_i)   (same-message agg)

Hash-to-curve is deterministic try-and-increment over SM3 (the reference
signs 32-byte SM3 digests directly, src/consensus.rs:390-395; its
`common_ref` domain string — "" in the reference, src/consensus.rs:351 — is
the `domain` parameter here).  Not constant-time: simulation/benchmark
posture, keys stay host-side (SURVEY.md §7 hard-parts note e).
"""

from __future__ import annotations

import functools
from typing import Iterable, List, Sequence, Tuple

from ..core.sm3 import sm3_hash

# --------------------------------------------------------------------------
# Parameters
# --------------------------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative); |x| drives the Miller loop and final exp.
X_ABS = 0xD201000000010000
G1_COFACTOR = 0x396C8C005555E1568C00AAAB0000AAAB

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# --------------------------------------------------------------------------
# Fq and the Fq2 / Fq6 / Fq12 tower
#   Fq2  = Fq[u]  / (u² + 1)
#   Fq6  = Fq2[v] / (v³ − ξ),  ξ = u + 1
#   Fq12 = Fq6[w] / (w² − v)        (so w⁶ = ξ)
# Elements are plain tuples: Fq2 = (a, b); Fq6 = (c0, c1, c2); Fq12 = (d0, d1).
# --------------------------------------------------------------------------

Fq2 = Tuple[int, int]
Fq6 = Tuple[Fq2, Fq2, Fq2]
Fq12 = Tuple[Fq6, Fq6]

FQ2_ZERO: Fq2 = (0, 0)
FQ2_ONE: Fq2 = (1, 0)
FQ6_ZERO: Fq6 = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE: Fq6 = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)
FQ12_ZERO: Fq12 = (FQ6_ZERO, FQ6_ZERO)
FQ12_ONE: Fq12 = (FQ6_ONE, FQ6_ZERO)


def fq_inv(a: int) -> int:
    return pow(a, -1, P)


def fq_sqrt(a: int):
    """Square root in Fq (p ≡ 3 mod 4), or None if a is a non-residue."""
    a %= P
    if a == 0:
        return 0
    s = pow(a, (P + 1) // 4, P)
    return s if s * s % P == a else None


def fq2_add(a: Fq2, b: Fq2) -> Fq2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fq2_sub(a: Fq2, b: Fq2) -> Fq2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fq2_neg(a: Fq2) -> Fq2:
    return (-a[0] % P, -a[1] % P)


def fq2_mul(a: Fq2, b: Fq2) -> Fq2:
    # (a0 + a1 u)(b0 + b1 u) with u² = −1 (Karatsuba).
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fq2_sq(a: Fq2) -> Fq2:
    # (a0² − a1²) + 2 a0 a1 u
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, 2 * a[0] * a[1] % P)


def fq2_scalar(a: Fq2, k: int) -> Fq2:
    return (a[0] * k % P, a[1] * k % P)


def fq2_conj(a: Fq2) -> Fq2:
    return (a[0], -a[1] % P)


def fq2_inv(a: Fq2) -> Fq2:
    # 1/(a0 + a1 u) = (a0 − a1 u) / (a0² + a1²)
    norm_inv = fq_inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * norm_inv % P, -a[1] * norm_inv % P)


def fq2_mul_xi(a: Fq2) -> Fq2:
    # multiply by ξ = 1 + u:  (a0 − a1) + (a0 + a1) u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fq2_is_zero(a: Fq2) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


def fq2_sqrt(a: Fq2):
    """Square root in Fq2 (u² = −1), or None.  Complex-sqrt formula:
    for a = x + y·u, with s = sqrt(x² + y²): sqrt(a) = t + (y / 2t)·u where
    t = sqrt((x ± s)/2)."""
    x, y = a[0] % P, a[1] % P
    if y == 0:
        t = fq_sqrt(x)
        if t is not None:
            return (t, 0)
        t = fq_sqrt(-x % P)
        if t is None:
            return None
        return (0, t)
    s = fq_sqrt((x * x + y * y) % P)
    if s is None:
        return None
    inv2 = fq_inv(2)
    for sign in (s, -s % P):
        alpha = (x + sign) * inv2 % P
        t = fq_sqrt(alpha)
        if t is not None and t != 0:
            res = (t, y * fq_inv(2 * t % P) % P)
            if fq2_sq(res) == (x, y):
                return res
    return None


def fq6_add(a: Fq6, b: Fq6) -> Fq6:
    return (fq2_add(a[0], b[0]), fq2_add(a[1], b[1]), fq2_add(a[2], b[2]))


def fq6_sub(a: Fq6, b: Fq6) -> Fq6:
    return (fq2_sub(a[0], b[0]), fq2_sub(a[1], b[1]), fq2_sub(a[2], b[2]))


def fq6_neg(a: Fq6) -> Fq6:
    return (fq2_neg(a[0]), fq2_neg(a[1]), fq2_neg(a[2]))


def fq6_mul(a: Fq6, b: Fq6) -> Fq6:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    # c0 = t0 + ξ·((a1+a2)(b1+b2) − t1 − t2)
    c0 = fq2_add(t0, fq2_mul_xi(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), t1), t2)))
    # c1 = (a0+a1)(b0+b1) − t0 − t1 + ξ·t2
    c1 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), t0), t1),
        fq2_mul_xi(t2))
    # c2 = (a0+a2)(b0+b2) − t0 − t2 + t1
    c2 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), t0), t2), t1)
    return (c0, c1, c2)


def fq6_mul_v(a: Fq6) -> Fq6:
    # multiply by v:  (c0, c1, c2) → (ξ·c2, c0, c1)
    return (fq2_mul_xi(a[2]), a[0], a[1])


def fq6_inv(a: Fq6) -> Fq6:
    a0, a1, a2 = a
    c0 = fq2_sub(fq2_sq(a0), fq2_mul_xi(fq2_mul(a1, a2)))
    c1 = fq2_sub(fq2_mul_xi(fq2_sq(a2)), fq2_mul(a0, a1))
    c2 = fq2_sub(fq2_sq(a1), fq2_mul(a0, a2))
    t = fq2_add(fq2_mul(a0, c0),
                fq2_mul_xi(fq2_add(fq2_mul(a2, c1), fq2_mul(a1, c2))))
    t_inv = fq2_inv(t)
    return (fq2_mul(c0, t_inv), fq2_mul(c1, t_inv), fq2_mul(c2, t_inv))


def fq12_add(a: Fq12, b: Fq12) -> Fq12:
    return (fq6_add(a[0], b[0]), fq6_add(a[1], b[1]))


def fq12_mul(a: Fq12, b: Fq12) -> Fq12:
    a0, a1 = a
    b0, b1 = b
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    # (a0 b0 + v·a1 b1) + ((a0+a1)(b0+b1) − t0 − t1)·w
    c0 = fq6_add(t0, fq6_mul_v(t1))
    c1 = fq6_sub(fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fq12_sq(a: Fq12) -> Fq12:
    return fq12_mul(a, a)


def fq12_conj(a: Fq12) -> Fq12:
    return (a[0], fq6_neg(a[1]))


def fq12_inv(a: Fq12) -> Fq12:
    a0, a1 = a
    t = fq6_inv(fq6_sub(fq6_mul(a0, a0), fq6_mul_v(fq6_mul(a1, a1))))
    return (fq6_mul(a0, t), fq6_neg(fq6_mul(a1, t)))


def fq12_pow(a: Fq12, e: int) -> Fq12:
    if e < 0:
        return fq12_pow(fq12_inv(a), -e)
    result = FQ12_ONE
    while e:
        if e & 1:
            result = fq12_mul(result, a)
        a = fq12_sq(a)
        e >>= 1
    return result


# Embeddings into Fq12.  An Fq element sits in the Fq2 c0 slot; an Fq2
# element x+yu sits in the Fq6 c0 slot of the Fq12 c0 slot.

def fq_to_fq12(a: int) -> Fq12:
    return (((a % P, 0), FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)


def fq2_to_fq12(a: Fq2) -> Fq12:
    return ((a, FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)


# --------------------------------------------------------------------------
# Curve arithmetic.
# G1: y² = x³ + 4 over Fq.  G2 (twist E'): y² = x³ + 4ξ over Fq2.
# Points are affine tuples or None (infinity); generic over the field ops.
# --------------------------------------------------------------------------

class _FieldOps:
    def __init__(self, add, sub, neg, mul, sq, inv, zero, one, scalar):
        self.add, self.sub, self.neg, self.mul = add, sub, neg, mul
        self.sq, self.inv, self.zero, self.one = sq, inv, zero, one
        self.scalar = scalar


_FQ_OPS = _FieldOps(
    add=lambda a, b: (a + b) % P, sub=lambda a, b: (a - b) % P,
    neg=lambda a: -a % P, mul=lambda a, b: a * b % P,
    sq=lambda a: a * a % P, inv=fq_inv, zero=0, one=1,
    scalar=lambda a, k: a * k % P)
_FQ2_OPS = _FieldOps(
    add=fq2_add, sub=fq2_sub, neg=fq2_neg, mul=fq2_mul, sq=fq2_sq,
    inv=fq2_inv, zero=FQ2_ZERO, one=FQ2_ONE, scalar=fq2_scalar)
_FQ12_OPS = _FieldOps(
    add=fq12_add, sub=lambda a, b: fq12_add(a, (fq6_neg(b[0]), fq6_neg(b[1]))),
    neg=lambda a: (fq6_neg(a[0]), fq6_neg(a[1])), mul=fq12_mul, sq=fq12_sq,
    inv=fq12_inv, zero=FQ12_ZERO, one=FQ12_ONE,
    scalar=lambda a, k: fq12_mul(a, fq_to_fq12(k)))


def _pt_double(pt, ops):
    if pt is None:
        return None
    x, y = pt
    if y == ops.zero:
        return None
    lam = ops.mul(ops.scalar(ops.sq(x), 3), ops.inv(ops.scalar(y, 2)))
    x3 = ops.sub(ops.sq(lam), ops.scalar(x, 2))
    y3 = ops.sub(ops.mul(lam, ops.sub(x, x3)), y)
    return (x3, y3)


def _pt_add(p1, p2, ops):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return _pt_double(p1, ops)
        return None
    lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    x3 = ops.sub(ops.sub(ops.sq(lam), x1), x2)
    y3 = ops.sub(ops.mul(lam, ops.sub(x1, x3)), y1)
    return (x3, y3)


def _pt_neg(pt, ops):
    if pt is None:
        return None
    return (pt[0], ops.neg(pt[1]))


def _pt_mul(pt, k, ops):
    if k < 0:
        return _pt_mul(_pt_neg(pt, ops), -k, ops)
    result = None
    while k:
        if k & 1:
            result = _pt_add(result, pt, ops)
        pt = _pt_double(pt, ops)
        k >>= 1
    return result


# Public G1/G2 wrappers.

def g1_add(p1, p2):
    return _pt_add(p1, p2, _FQ_OPS)


def g1_mul(pt, k):
    return _pt_mul(pt, k % R if pt is not None else k, _FQ_OPS)


def g1_neg(pt):
    return _pt_neg(pt, _FQ_OPS)


def g2_add(p1, p2):
    return _pt_add(p1, p2, _FQ2_OPS)


def g2_mul(pt, k):
    return _pt_mul(pt, k % R if pt is not None else k, _FQ2_OPS)


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x * x + 4)) % P == 0


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    b = fq2_mul_xi((4, 0))  # 4ξ = 4 + 4u
    return fq2_sub(fq2_sq(y), fq2_add(fq2_mul(fq2_sq(x), x), b)) == FQ2_ZERO


def g1_in_subgroup(pt) -> bool:
    return g1_is_on_curve(pt) and _pt_mul(pt, R, _FQ_OPS) is None


def g2_in_subgroup(pt) -> bool:
    return g2_is_on_curve(pt) and _pt_mul(pt, R, _FQ2_OPS) is None


# --------------------------------------------------------------------------
# Pairing: untwist G2 into E(Fq12), Miller loop over |x|, final exponentiation.
# Untwist (M-twist, ξ = w⁶): (x', y') → (x'/w², y'/w³).
# With the tower w² = v:  1/w² = 1/v = v²·ξ⁻¹;  1/w³ = 1/(v·w) = w·v·ξ⁻¹...
# computed once below via a generic Fq12 inversion for clarity.
# --------------------------------------------------------------------------

def _w_pow_inv(n: int) -> Fq12:
    """(w^n)⁻¹ in Fq12."""
    w: Fq12 = (FQ6_ZERO, FQ6_ONE)
    return fq12_inv(fq12_pow(w, n))


_W2_INV = _w_pow_inv(2)
_W3_INV = _w_pow_inv(3)


def untwist(pt):
    """Map a point on E'(Fq2) to E(Fq12)."""
    if pt is None:
        return None
    x, y = pt
    return (fq12_mul(fq2_to_fq12(x), _W2_INV), fq12_mul(fq2_to_fq12(y), _W3_INV))


def _line(p1, p2, at):
    """Evaluate the line through p1,p2 (or tangent if equal) at point `at`.
    All points on E(Fq12), affine."""
    ops = _FQ12_OPS
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = at
    if x1 != x2:
        lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    elif y1 == y2:
        lam = ops.mul(ops.scalar(ops.sq(x1), 3), ops.inv(ops.scalar(y1, 2)))
    else:  # vertical line
        return ops.sub(xt, x1)
    return ops.sub(ops.sub(yt, y1), ops.mul(lam, ops.sub(xt, x1)))


def miller_loop(q, p) -> Fq12:
    """f_{|x|,Q}(P), conjugated for the negative BLS parameter.
    q, p are points on E(Fq12) (q from untwist(G2 point), p from G1)."""
    if q is None or p is None:
        return FQ12_ONE
    ops = _FQ12_OPS
    f = FQ12_ONE
    r_pt = q
    for bit in bin(X_ABS)[3:]:
        f = fq12_mul(fq12_sq(f), _line(r_pt, r_pt, p))
        r_pt = _pt_add(r_pt, r_pt, ops)
        if bit == "1":
            f = fq12_mul(f, _line(r_pt, q, p))
            r_pt = _pt_add(r_pt, q, ops)
    # x < 0: invert; post-final-exp, conjugation == inversion, and the
    # difference is killed by the final exponentiation.
    return fq12_conj(f)


def _frob_gamma() -> List[Fq2]:
    """γ^k = ξ^(k·(p−1)/6) for k = 1..5 — the Frobenius twist constants of
    the 1, v, v², w, vw, v²w basis."""
    xi: Fq2 = (1, 1)
    e = (P - 1) // 6
    g = _fq2_pow(xi, e)
    out = [g]
    for _ in range(4):
        out.append(fq2_mul(out[-1], g))
    return out


def _fq2_pow(a: Fq2, e: int) -> Fq2:
    result: Fq2 = FQ2_ONE
    while e:
        if e & 1:
            result = fq2_mul(result, a)
        a = fq2_sq(a)
        e >>= 1
    return result


_GAMMA = None


def fq12_frobenius(f: Fq12) -> Fq12:
    """f^p via coefficient conjugation + twist constants (γ table built
    lazily)."""
    global _GAMMA
    if _GAMMA is None:
        _GAMMA = _frob_gamma()
    g = _GAMMA
    (a0, a1, a2), (b0, b1, b2) = f
    return (
        (fq2_conj(a0), fq2_mul(fq2_conj(a1), g[1]), fq2_mul(fq2_conj(a2), g[3])),
        (fq2_mul(fq2_conj(b0), g[0]), fq2_mul(fq2_conj(b1), g[2]),
         fq2_mul(fq2_conj(b2), g[4])),
    )


def _cyc_pow(f: Fq12, e: int) -> Fq12:
    """f^e for f in the cyclotomic subgroup (where f⁻¹ = conj(f)), signed
    exponent."""
    if e < 0:
        return _cyc_pow(fq12_conj(f), -e)
    result = FQ12_ONE
    while e:
        if e & 1:
            result = fq12_mul(result, f)
        f = fq12_sq(f)
        e >>= 1
    return result


def final_exponentiation(f: Fq12) -> Fq12:
    """f^(3·(p¹²−1)/r) — the standard *cubed* final exponentiation: the
    BLS12 parameter decomposition (x−1)²·(x+p)·(x²+p²−1) + 3 equals three
    times the hard exponent, and since gcd(3, r) = 1 the cube changes no
    `== 1` or cross-pairing equality check, while costing ~5 64-bit
    exponentiations instead of one 4569-bit one.  Easy part by
    inversion + Frobenius."""
    # Easy part: f^((p⁶−1)(p²+1)).  m = f^(p⁶−1) = conj(f)·f⁻¹, then
    # m^(p²)·m via two Frobenius applications.
    m = fq12_mul(fq12_conj(f), fq12_inv(f))
    m = fq12_mul(fq12_frobenius(fq12_frobenius(m)), m)
    # Hard part (m is now cyclotomic: m⁻¹ = conj(m)).
    x = -X_ABS
    t0 = _cyc_pow(m, x - 1)                       # m^(x−1)
    t1 = _cyc_pow(t0, x - 1)                      # m^((x−1)²)
    t2 = fq12_mul(_cyc_pow(t1, x), fq12_frobenius(t1))   # ^(x+p)
    t3 = fq12_mul(
        fq12_mul(_cyc_pow(_cyc_pow(t2, x), x),
                 fq12_frobenius(fq12_frobenius(t2))),
        fq12_conj(t2))                            # ^(x²+p²−1)
    return fq12_mul(t3, fq12_mul(fq12_sq(m), m))  # · m³


def final_exponentiation_naive(f: Fq12) -> Fq12:
    """Reference-grade slow path (plain square-and-multiply over the full
    hard exponent); kept as the oracle for the fast chain above."""
    f1 = fq12_mul(fq12_conj(f), fq12_inv(f))
    f2 = fq12_mul(fq12_pow(f1, P * P), f1)
    hard = (P**4 - P**2 + 1) // R
    return fq12_pow(f2, hard)


def pairing(q, p) -> Fq12:
    """e(P, Q)³ with P ∈ G1, Q ∈ G2' (affine Fq/Fq2 points).

    NOTE: the fast final_exponentiation computes f^(3·e), so this returns
    the CUBE of the standard ate pairing value.  Since 3 ∤ r, cubing is a
    bijection on the r-th roots of unity: every in-repo use (== 1 tests,
    cross-pairing equality) is invariant.  For byte-level comparison
    against external pairing test vectors, use
    final_exponentiation_naive(miller_loop(...)) instead."""
    return final_exponentiation(miller_loop(untwist(q), (fq_to_fq12(p[0]), fq_to_fq12(p[1]))))


def multi_pairing_is_one_pure(
        pairs: Iterable[Tuple[object, object]]) -> bool:
    """Π e(P_i, Q_i) == 1, sharing one final exponentiation — the
    pure-Python path (the correctness oracle for the native backend).
    pairs: iterable of (g1_point, g2_point)."""
    f = FQ12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue
        f = fq12_mul(f, miller_loop(untwist(q), (fq_to_fq12(p[0]), fq_to_fq12(p[1]))))
    return final_exponentiation(f) == FQ12_ONE


def multi_pairing_is_one(pairs: Iterable[Tuple[object, object]]) -> bool:
    """Π e(P_i, Q_i) == 1 — dispatches to the native C backend
    (csrc/bls381.c, ~13x faster per check) when a compiler is around,
    falling back to the pure path.  Every pairing consumer (verify,
    aggregate-verify, the TPU provider's per-batch checks) funnels
    through here."""
    from . import native
    if native.available():
        return native.multi_pairing_is_one(list(pairs))
    return multi_pairing_is_one_pure(pairs)


# --------------------------------------------------------------------------
# Serialization (ZCash BLS12-381 format: 48B G1 / 96B G2 compressed,
# flag bits in the top 3 bits of byte 0: compressed, infinity, y-sign).
# --------------------------------------------------------------------------

_FLAG_COMPRESSED = 0x80
_FLAG_INFINITY = 0x40
_FLAG_SIGN = 0x20


def _y_is_lexicographically_largest_fq(y: int) -> bool:
    return y > (P - 1) // 2


def _y_is_lexicographically_largest_fq2(y: Fq2) -> bool:
    if y[1] != 0:
        return y[1] > (P - 1) // 2
    return y[0] > (P - 1) // 2


def g1_compress(pt) -> bytes:
    if pt is None:
        return bytes([_FLAG_COMPRESSED | _FLAG_INFINITY]) + b"\x00" * 47
    x, y = pt
    flags = _FLAG_COMPRESSED
    if _y_is_lexicographically_largest_fq(y):
        flags |= _FLAG_SIGN
    raw = bytearray(x.to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def g1_decompress(data: bytes):
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _FLAG_COMPRESSED:
        raise ValueError("uncompressed G1 encoding not supported")
    if flags & _FLAG_INFINITY:
        if any(data[1:]) or flags & _FLAG_SIGN or data[0] & 0x1F:
            raise ValueError("malformed G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = fq_sqrt((x * x * x + 4) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if _y_is_lexicographically_largest_fq(y) != bool(flags & _FLAG_SIGN):
        y = -y % P
    return (x, y)


def g2_compress(pt) -> bytes:
    if pt is None:
        return bytes([_FLAG_COMPRESSED | _FLAG_INFINITY]) + b"\x00" * 95
    x, y = pt
    flags = _FLAG_COMPRESSED
    if _y_is_lexicographically_largest_fq2(y):
        flags |= _FLAG_SIGN
    raw = bytearray(x[1].to_bytes(48, "big") + x[0].to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def g2_decompress(data: bytes):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _FLAG_COMPRESSED:
        raise ValueError("uncompressed G2 encoding not supported")
    if flags & _FLAG_INFINITY:
        if any(data[1:]) or flags & _FLAG_SIGN or data[0] & 0x1F:
            raise ValueError("malformed G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x: Fq2 = (x0, x1)
    rhs = fq2_add(fq2_mul(fq2_sq(x), x), fq2_mul_xi((4, 0)))
    y = fq2_sqrt(rhs)
    if y is None:
        raise ValueError("G2 x not on curve")
    if _y_is_lexicographically_largest_fq2(y) != bool(flags & _FLAG_SIGN):
        y = fq2_neg(y)
    return (x, y)


# --------------------------------------------------------------------------
# Hash-to-G1 (deterministic try-and-increment over SM3) and the signature
# scheme surface.
# --------------------------------------------------------------------------

@functools.lru_cache(maxsize=4096)
def hash_to_g1(message: bytes, domain: bytes = b""):
    """Deterministic map bytes → G1 r-torsion point: RFC 9380
    BLS12381G1_XMD:SHA-256_SSWU_RO_ (crypto/hash_to_curve.py) — the
    standards hash the reference reaches through blst's hash-to-curve
    (src/consensus.rs:390-395).  `domain` is the DST; the reference's
    hard-coded common_ref = "" (src/consensus.rs:351) maps to the
    standard basic-scheme ciphersuite tag.  lru-cached: every verify of
    a batch on the same vote hash re-derives the same point."""
    from .hash_to_curve import DEFAULT_DST, hash_to_curve_g1
    return hash_to_curve_g1(message, domain or DEFAULT_DST)


def hash_to_g1_try_increment(message: bytes, domain: bytes = b""):
    """The round-1/2 try-and-increment map, kept as a non-standard
    cross-check of scheme-level properties (tests compare both maps'
    sign/verify behavior; new signatures use SSWU above)."""
    for ctr in range(256):
        seed = domain + message + bytes([ctr])
        h = sm3_hash(seed + b"\x00") + sm3_hash(seed + b"\x01")
        x = int.from_bytes(h, "big") % P
        rhs = (x * x * x + 4) % P
        y = fq_sqrt(rhs)
        if y is None:
            continue
        if sm3_hash(seed + b"\x02")[0] & 1:
            y = -y % P
        pt = g1_mul((x, y), G1_COFACTOR)
        if pt is not None:
            return pt
    raise ValueError("hash_to_g1 failed to find a point (probability ~2^-256)")


def sk_to_pk(sk: int) -> bytes:
    """Serialize the G2 public key for scalar sk (96B; doubles as the
    validator address, reference src/consensus.rs:352-357)."""
    return g2_compress(g2_mul(G2_GEN, sk % R))


def sign(sk: int, message: bytes, domain: bytes = b"") -> bytes:
    """sig = sk · H(m) ∈ G1, 48 bytes compressed."""
    return g1_compress(g1_mul(hash_to_g1(message, domain), sk % R))


def verify(pk_bytes: bytes, message: bytes, sig_bytes: bytes,
           domain: bytes = b"", check_subgroup: bool = True) -> bool:
    """e(sig, G2gen) == e(H(m), pk), via e(sig, −G2gen)·e(H(m), pk) == 1."""
    try:
        sig = g1_decompress(sig_bytes)
        pk = g2_decompress(pk_bytes)
    except ValueError:
        return False
    if sig is None or pk is None:
        return False
    if check_subgroup and not (g1_in_subgroup(sig) and g2_in_subgroup(pk)):
        return False
    h = hash_to_g1(message, domain)
    neg_g2 = (G2_GEN[0], fq2_neg(G2_GEN[1]))
    return multi_pairing_is_one([(sig, neg_g2), (h, pk)])


def aggregate_signatures(sig_bytes_list: Sequence[bytes]) -> bytes:
    """Sum the G1 signatures (reference src/consensus.rs:418-444)."""
    agg = None
    for sb in sig_bytes_list:
        agg = g1_add(agg, g1_decompress(sb))
    return g1_compress(agg)


def aggregate_pubkeys(pk_bytes_list: Sequence[bytes]) -> bytes:
    """Sum the G2 public keys (reference src/consensus.rs:365-383)."""
    agg = None
    for pb in pk_bytes_list:
        agg = g2_add(agg, g2_decompress(pb))
    return g2_compress(agg)


def aggregate_verify_same_message(
        pk_bytes_list: Sequence[bytes], message: bytes, agg_sig_bytes: bytes,
        domain: bytes = b"", check_subgroup: bool = True) -> bool:
    """Same-message aggregate verification: e(agg_sig, G2gen) ==
    e(H(m), Σ pk_i) — the QC verification shape of the reference
    (src/consensus.rs:446-462)."""
    try:
        agg_sig = g1_decompress(agg_sig_bytes)
        pks = [g2_decompress(pb) for pb in pk_bytes_list]
    except ValueError:
        return False
    if agg_sig is None or not pks:
        return False
    if check_subgroup:
        if not g1_in_subgroup(agg_sig):
            return False
        if any(pk is None or not g2_in_subgroup(pk) for pk in pks):
            return False
    agg_pk = None
    for pk in pks:
        agg_pk = g2_add(agg_pk, pk)
    if agg_pk is None:
        return False
    h = hash_to_g1(message, domain)
    neg_g2 = (G2_GEN[0], fq2_neg(G2_GEN[1]))
    return multi_pairing_is_one([(agg_sig, neg_g2), (h, agg_pk)])
