"""RFC 9380 hash-to-curve for BLS12-381 G1:
BLS12381G1_XMD:SHA-256_SSWU_RO_ (§8.8.1).

The reference signs via blst's hash-to-curve (reference
src/consensus.rs:390-395, through ophelia-blst); round 1/2 of this
rebuild used try-and-increment hashing, which is capability-equivalent
but not byte-interoperable with any standards-conformant BLS stack.
This module is the standards path: expand_message_xmd(SHA-256) →
hash_to_field(m=1, L=64) → simplified SWU on the 11-isogenous curve
E': y² = x³ + A'x + B' (Z = 11) → 11-isogeny → clear cofactor.

The isogeny coefficients below are NOT transcribed from the RFC
appendix: they are derived from first principles by
scripts/derive_g1_isogeny.py (division polynomial → rational order-11
kernel → Vélu's formulas → isomorphism normalization pinned by the
RFC's k_(1,0)), and verified structurally (image on E, homomorphism)
plus by the RFC known-answer vectors in tests/test_hash_to_curve.py.
Regenerate with: python scripts/derive_g1_isogeny.py

Host-side by design: hashing to the curve is O(1) per message per round
(one point for the common vote hash), while the O(N) work — per-vote
verification MSMs — lives on device (crypto/tpu_provider.py).
"""

import hashlib
from typing import List, Optional, Tuple

P = int(
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f624"
    "1eabfffeb153ffffb9feffffffffaaab", 16)

# Isogenous curve E' (RFC 9380 §8.8.1) and SWU constant Z = 11.
ISO_A = int(
    "144698a3b8e9433d693a02c96d4982b0ea985383ee66a8d8e8981aefd881ac98"
    "936f8da0e0f97f5cf428082d584c1d", 16)
ISO_B = int(
    "12e2908d11688030018b12e8753eee3b2016c1f0f24f4070a0b9c14fcef35ef5"
    "5a23215a316ceaa5d1cc48e98e172be0", 16)
SWU_Z = 11

#: G1 effective cofactor (RFC 9380 §8.8.1): 1 − z for the BLS12 parameter
#: z = −0xd201000000010000.
H_EFF = 0xD201000000010001

#: Default signing domain-separation tag when the deployment supplies no
#: common_ref (the reference hard-codes common_ref = "",
#: src/consensus.rs:351): the standard basic-scheme ciphersuite tag.
DEFAULT_DST = b"BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_"

# 11-isogeny E' → E rational maps (coefficients low-degree-first),
# derived by scripts/derive_g1_isogeny.py — see module docstring.
ISO_X_NUM = [
    0x11a05f2b1e833340b809101dd99815856b303e88a2d7005ff2627b56cdb4e2c85610c2d5f2e62d6eaeac1662734649b7,
    0x17294ed3e943ab2f0588bab22147a81c7c17e75b2f6a8417f565e33c70d1e86b4838f2a6f318c356e834eef1b3cb83bb,
    0x0d54005db97678ec1d1048c5d10a9a1bce032473295983e56878e501ec68e25c958c3e3d2a09729fe0179f9dac9edcb0,
    0x1778e7166fcc6db74e0609d307e55412d7f5e4656a8dbf25f1b33289f1b330835336e25ce3107193c5b388641d9b6861,
    0x0e99726a3199f4436642b4b3e4118e5499db995a1257fb3f086eeb65982fac18985a286f301e77c451154ce9ac8895d9,
    0x1630c3250d7313ff01d1201bf7a74ab5db3cb17dd952799b9ed3ab9097e68f90a0870d2dcae73d19cd13c1c66f652983,
    0x0d6ed6553fe44d296a3726c38ae652bfb11586264f0f8ce19008e218f9c86b2a8da25128c1052ecaddd7f225a139ed84,
    0x17b81e7701abdbe2e8743884d1117e53356de5ab275b4db1a682c62ef0f2753339b7c8f8c8f475af9ccb5618e3f0c88e,
    0x080d3cf1f9a78fc47b90b33563be990dc43b756ce79f5574a2c596c928c5d1de4fa295f296b74e956d71986a8497e317,
    0x169b1f8e1bcfa7c42e0c37515d138f22dd2ecb803a0c5c99676314baf4bb1b7fa3190b2edc0327797f241067be390c9e,
    0x10321da079ce07e272d8ec09d2565b0dfa7dccdde6787f96d50af36003b14866f69b771f8c285decca67df3f1605fb7b,
    0x06e08c248e260e70bd1e962381edee3d31d79d7e22c837bc23c0bf1bc24c6b68c24b1b80b64d391fa9c8ba2e8ba2d229,
]
ISO_X_DEN = [
    0x08ca8d548cff19ae18b2e62f4bd3fa6f01d5ef4ba35b48ba9c9588617fc8ac62b558d681be343df8993cf9fa40d21b1c,
    0x12561a5deb559c4348b4711298e536367041e8ca0cf0800c0126c2588c48bf5713daa8846cb026e9e5c8276ec82b3bff,
    0x0b2962fe57a3225e8137e629bff2991f6f89416f5a718cd1fca64e00b11aceacd6a3d0967c94fedcfcc239ba5cb83e19,
    0x03425581a58ae2fec83aafef7c40eb545b08243f16b1655154cca8abc28d6fd04976d5243eecf5c4130de8938dc62cd8,
    0x13a8e162022914a80a6f1d5f43e7a07dffdfc759a12062bb8d6b44e833b306da9bd29ba81f35781d539d395b3532a21e,
    0x0e7355f8e4e667b955390f7f0506c6e9395735e9ce9cad4d0a43bcef24b8982f7400d24bc4228f11c02df9a29f6304a5,
    0x0772caacf16936190f3e0c63e0596721570f5799af53a1894e2e073062aede9cea73b3538f0de06cec2574496ee84a3a,
    0x14a7ac2a9d64a8b230b3f5b074cf01996e7f63c21bca68a81996e1cdf9822c580fa5b9489d11e2d311f7d99bbdcc5a5e,
    0x0a10ecf6ada54f825e920b3dafc7a3cce07f8d1d7161366b74100da67f39883503826692abba43704776ec3a79a1d641,
    0x095fc13ab9e92ad4476d6e3eb3a56680f682b4ee96f7d03776df533978f31c1593174e4b4b7865002d6384d168ecdd0a,
    0x000000000000000000000000000000000000000000000000000000000000000000000000000000000000000000000001,
]
ISO_Y_NUM = [
    0x090d97c81ba24ee0259d1f094980dcfa11ad138e48a869522b52af6c956543d3cd0c7aee9b3ba3c2be9845719707bb33,
    0x134996a104ee5811d51036d776fb46831223e96c254f383d0f906343eb67ad34d6c56711962fa8bfe097e75a2e41c696,
    0x00cc786baa966e66f4a384c86a3b49942552e2d658a31ce2c344be4b91400da7d26d521628b00523b8dfe240c72de1f6,
    0x01f86376e8981c217898751ad8746757d42aa7b90eeb791c09e4a3ec03251cf9de405aba9ec61deca6355c77b0e5f4cb,
    0x08cc03fdefe0ff135caf4fe2a21529c4195536fbe3ce50b879833fd221351adc2ee7f8dc099040a841b6daecf2e8fedb,
    0x16603fca40634b6a2211e11db8f0a6a074a7d0d4afadb7bd76505c3d3ad5544e203f6326c95a807299b23ab13633a5f0,
    0x04ab0b9bcfac1bbcb2c977d027796b3ce75bb8ca2be184cb5231413c4d634f3747a87ac2460f415ec961f8855fe9d6f2,
    0x0987c8d5333ab86fde9926bd2ca6c674170a05bfe3bdd81ffd038da6c26c842642f64550fedfe935a15e4ca31870fb29,
    0x09fc4018bd96684be88c9e221e4da1bb8f3abd16679dc26c1e8b6e6a1f20cabe69d65201c78607a360370e577bdba587,
    0x0e1bba7a1186bdb5223abde7ada14a23c42a0ca7915af6fe06985e7ed1e4d43b9b3f7055dd4eba6f2bafaaebca731c30,
    0x19713e47937cd1be0dfd0b8f1d43fb93cd2fcbcb6caf493fd1183e416389e61031bf3a5cce3fbafce813711ad011c132,
    0x18b46a908f36f6deb918c143fed2edcc523559b8aaf0c2462e6bfe7f911f643249d9cdf41b44d606ce07c8a4d0074d8e,
    0x0b182cac101b9399d155096004f53f447aa7b12a3426b08ec02710e807b4633f06c851c1919211f20d4c04f00b971ef8,
    0x0245a394ad1eca9b72fc00ae7be315dc757b3b080d4c158013e6632d3c40659cc6cf90ad1c232a6442d9d3f5db980133,
    0x05c129645e44cf1102a159f748c4a3fc5e673d81d7e86568d9ab0f5d396a7ce46ba1049b6579afb7866b1e715475224b,
    0x15e6be4e990f03ce4ea50b3b42df2eb5cb181d8f84965a3957add4fa95af01b2b665027efec01c7704b456be69c8b604,
]
ISO_Y_DEN = [
    0x16112c4c3a9c98b252181140fad0eae9601a6de578980be6eec3232b5be72e7a07f3688ef60c206d01479253b03663c1,
    0x1962d75c2381201e1a0cbd6c43c348b885c84ff731c4d59ca4a10356f453e01f78a4260763529e3532f6102c2e49a03d,
    0x058df3306640da276faaae7d6e8eb15778c4855551ae7f310c35a5dd279cd2eca6757cd636f96f891e2538b53dbf67f2,
    0x16b7d288798e5395f20d23bf89edb4d1d115c5dbddbcd30e123da489e726af41727364f2c28297ada8d26d98445f5416,
    0x0be0e079545f43e4b00cc912f8228ddcc6d19c9f0f69bbb0542eda0fc9dec916a20b15dc0fd2ededda39142311a5001d,
    0x08d9e5297186db2d9fb266eaac783182b70152c65550d881c5ecd87b6f0f5a6449f38db9dfa9cce202c6477faaf9b7ac,
    0x166007c08a99db2fc3ba8734ace9824b5eecfdfa8d0cf8ef5dd365bc400a0051d5fa9c01a58b1fb93d1a1399126a775c,
    0x16a3ef08be3ea7ea03bcddfabba6ff6ee5a4375efa1f4fd7feb34fd206357132b920f5b00801dee460ee415a15812ed9,
    0x1866c8ed336c61231a1be54fd1d74cc4f9fb0ce4c6af5920abc5750c4bf39b4852cfe2f7bb9248836b233d9d55535d4a,
    0x167a55cda70a6e1cea820597d94a84903216f763e13d87bb5308592e7ea7d4fbc7385ea3d529b35e346ef48bb8913f55,
    0x04d2f259eea405bd48f010a01ad2911d9c6dd039bb61a6290e591b36e636a5c871a5c29f4f83060400f8b49cba8f6aa8,
    0x0accbb67481d033ff5852c1e48c50c477f94ff8aefce42d28c0f9a88cea7913516f968986f7ebbea9684b529e2561092,
    0x0ad6b9514c767fe3c3613144b45f1496543346d98adf02267d5ceef9a00d9b8693000763e3b90ac11e99b138573345cc,
    0x02660400eb2e4f3b628bdd0d53cd76f2bf565b94e72927c1cb748df27942480e420517bd8714cc80d1fadc1326ed06f7,
    0x0e0fa1d816ddc03e6b24255e0d7819c171c40f65e273b853324efcd6356caa205ca2f570f13497804415473a1d634b8f,
    0x000000000000000000000000000000000000000000000000000000000000000000000000000000000000000000000001,
]


# ---------------------------------------------------------------------------
# expand_message_xmd (RFC 9380 §5.3.1, SHA-256)
# ---------------------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = -(-len_in_bytes // 32)
    assert ell <= 255 and len_in_bytes <= 65535
    dst_prime = dst + bytes([len(dst)])
    b0 = hashlib.sha256(
        b"\x00" * 64 + msg + len_in_bytes.to_bytes(2, "big") + b"\x00"
        + dst_prime).digest()
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [bi]
    for i in range(2, ell + 1):
        bi = hashlib.sha256(bytes(a ^ b for a, b in zip(b0, bi))
                            + bytes([i]) + dst_prime).digest()
        out.append(bi)
    return b"".join(out)[:len_in_bytes]


def hash_to_field(msg: bytes, dst: bytes, count: int) -> List[int]:
    """count elements of Fp (m = 1, L = 64, RFC 9380 §5.2)."""
    uniform = expand_message_xmd(msg, dst, count * 64)
    return [int.from_bytes(uniform[i * 64:(i + 1) * 64], "big") % P
            for i in range(count)]


# ---------------------------------------------------------------------------
# Simplified SWU on E' + 11-isogeny to E (§6.6.2, §6.6.3)
# ---------------------------------------------------------------------------

def _sqrt(v: int) -> Optional[int]:
    r = pow(v, (P + 1) // 4, P)
    return r if r * r % P == v else None


def _sgn0(v: int) -> int:
    return v & 1


def map_to_curve_sswu(u: int) -> Tuple[int, int]:
    """u → a point on E' (never the identity)."""
    a, b, z = ISO_A, ISO_B, SWU_Z
    u2 = u * u % P
    tv1 = (z * z % P * (u2 * u2 % P) + z * u2) % P
    if tv1 == 0:
        x1 = b * pow(z * a % P, P - 2, P) % P
    else:
        x1 = (-b) % P * pow(a, P - 2, P) % P * (1 + pow(tv1, P - 2, P)) % P
    gx1 = (pow(x1, 3, P) + a * x1 + b) % P
    y = _sqrt(gx1)
    if y is not None:
        x = x1
    else:
        x = z * u2 % P * x1 % P
        gx2 = (pow(x, 3, P) + a * x + b) % P
        y = _sqrt(gx2)
        assert y is not None, "SWU: neither g(x1) nor g(x2) is square"
    if _sgn0(u) != _sgn0(y):
        y = (-y) % P
    return (x, y)


def _horner(coeffs: List[int], x: int) -> int:
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % P
    return acc


def iso_map(pt: Tuple[int, int]) -> Optional[Tuple[int, int]]:
    """The 11-isogeny E' → E; None (identity) at kernel points."""
    x, y = pt
    den = _horner(ISO_X_DEN, x)
    if den == 0:
        return None
    xo = _horner(ISO_X_NUM, x) * pow(den, P - 2, P) % P
    yo = y * _horner(ISO_Y_NUM, x) % P * pow(_horner(ISO_Y_DEN, x),
                                             P - 2, P) % P
    return (xo, yo)


def hash_to_curve_g1(msg: bytes, dst: bytes = DEFAULT_DST
                     ) -> Tuple[int, int]:
    """BLS12381G1_XMD:SHA-256_SSWU_RO_ (uniform encoding, two SWU maps).
    The two E' points add on E' and map through ONE isogeny evaluation —
    identical to mapping separately (the isogeny is a homomorphism,
    verified by scripts/derive_g1_isogeny.py) but half the iso cost."""
    from . import bls12381 as oracle  # lazy: avoid import cycle

    u0, u1 = hash_to_field(msg, dst, 2)
    q0 = map_to_curve_sswu(u0)
    q1 = map_to_curve_sswu(u1)
    r = _add_on_iso(q0, q1)
    pt = iso_map(r) if r is not None else None
    return oracle.g1_mul(pt, H_EFF)


def _add_on_iso(p1, p2):
    """Affine addition on E' (a = ISO_A): O(1) host arithmetic."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1 + ISO_A) * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)
