"""Device-batched ECDSA (secp256k1) and SM2 signature verification.

BASELINE.md configs 3 and 5 call for secp256k1 and SM2 fleets; the
reference is BLS-only (src/consensus.rs:336-337), so these providers are
new capability, built on the same curve-generic TPU stack as BLS/Ed25519
(ops/field.py + ops/curve.py + ops/weierstrass.py).

Verification equation per lane (no random-linear-combination — each lane
is checked independently and exactly, so there is no fallback pass):

  ECDSA:  R = (e/s)·G + (r/s)·Q,  accept iff R ≠ ∞ and R.x ≡ r (mod n)
  SM2:    R = s·G + t·Q, t = r+s, accept iff R ≠ ∞ and (e + R.x) ≡ r (mod n)

Both reduce to one dual-scalar multiplication u1·G + u2·Q (Shamir-
interleaved, shared doubling run) and an inversion-free affine-x test:
x1 ≡ c (mod n) for projective (X:Y:Z) holds iff X == ĉ·Z for some lift
ĉ ∈ {c, c+n} ∩ [0, p) — two field muls instead of a 256-square batched
inversion.

Scheme notes (documented deviations, both malleability-motivated):
* secp256k1 verification enforces **low-s** (s ≤ (n−1)/2, BIP-62 rule) —
  plain ECDSA accepts both (r, s) and (r, n−s); a consensus vote must
  not have two valid byte encodings.  `sign` always emits low-s.
* SM2 here signs the 32-byte hash directly (e = int(hash32)) — the GB/T
  32918.2 Z_A/user-id digest pipeline is the caller's concern; consensus
  vote hashes are already SM3 digests (core/sm3.py).

Signing is host-side with deterministic nonces (RFC 6979-shaped: k from
SM3(sk ‖ e ‖ ctr) mod n, retry on degenerate values); signing keys never
reach the device (SURVEY.md §7 hard part (e)).
"""

from __future__ import annotations

import functools
import logging
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compile_cache import enable as _enable_compile_cache
from ..core.sm3 import sm3_hash

_enable_compile_cache()

from ..ops import weierstrass as w
from ..ops.curve import int_to_bits_msb_np
from .provider import CryptoError
from .tpu_provider import _pad_to

_SCALAR_BITS = 256

logger = logging.getLogger("consensus_overlord_tpu.ecdsa_tpu")


# ---------------------------------------------------------------------------
# Host-side affine curve math (python ints): signing + single-verify oracle.
# ---------------------------------------------------------------------------

class HostCurve:
    """Short-Weierstrass arithmetic over python ints — the host oracle
    the device kernels are tested against, and the signing/verify path.

    The affine `add` keeps the textbook per-step-inversion form (it is
    the independent oracle device tests compare against); `mul` and
    `mul_add` run in Jacobian coordinates with a single final inversion —
    a ~25x speedup that keeps host signing/verification inside a
    consensus round's timers (one affine inversion costs ~50 µs in
    python; 512 of them per scalar-mul dominated everything)."""

    def __init__(self, p: int, a: int, b: int, n: int, gx: int, gy: int):
        self.p, self.a, self.b, self.n = p, a, b, n
        self.g = (gx, gy)
        assert p % 4 == 3  # sqrt by (p+1)/4 on both target curves

    def add(self, p1: Optional[Tuple[int, int]],
            p2: Optional[Tuple[int, int]]) -> Optional[Tuple[int, int]]:
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        P = self.p
        (x1, y1), (x2, y2) = p1, p2
        if x1 == x2:
            if (y1 + y2) % P == 0:
                return None
            lam = (3 * x1 * x1 + self.a) * pow(2 * y1, P - 2, P) % P
        else:
            lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
        x3 = (lam * lam - x1 - x2) % P
        return (x3, (lam * (x1 - x3) - y1) % P)

    # -- Jacobian internals (X/Z², Y/Z³); None = infinity -------------------

    def _jdbl(self, pt):
        if pt is None:
            return None
        P = self.p
        x, y, z = pt
        if y == 0:
            return None
        ysq = y * y % P
        s = 4 * x * ysq % P
        m = (3 * x * x + self.a * pow(z, 4, P)) % P
        x3 = (m * m - 2 * s) % P
        return (x3, (m * (s - x3) - 8 * ysq * ysq) % P, 2 * y * z % P)

    def _jadd(self, p1, p2):
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        P = self.p
        x1, y1, z1 = p1
        x2, y2, z2 = p2
        z1s, z2s = z1 * z1 % P, z2 * z2 % P
        u1, u2 = x1 * z2s % P, x2 * z1s % P
        s1, s2 = y1 * z2s * z2 % P, y2 * z1s * z1 % P
        if u1 == u2:
            if s1 != s2:
                return None
            return self._jdbl(p1)
        h = (u2 - u1) % P
        r = (s2 - s1) % P
        hs = h * h % P
        hc = hs * h % P
        u1hs = u1 * hs % P
        x3 = (r * r - hc - 2 * u1hs) % P
        return (x3, (r * (u1hs - x3) - s1 * hc) % P, h * z1 % P * z2 % P)

    def _jaffine(self, pt) -> Optional[Tuple[int, int]]:
        if pt is None:
            return None
        P = self.p
        x, y, z = pt
        zi = pow(z, P - 2, P)
        zis = zi * zi % P
        return (x * zis % P, y * zis * zi % P)

    def mul(self, k: int, pt: Optional[Tuple[int, int]]
            ) -> Optional[Tuple[int, int]]:
        if pt is None:
            return None
        k %= self.n
        acc = None
        j = (pt[0], pt[1], 1)
        for bit in bin(k)[2:] if k else "":
            acc = self._jdbl(acc)
            if bit == "1":
                acc = self._jadd(acc, j)
        return self._jaffine(acc)

    def mul_add(self, u1: int, u2: int, q: Tuple[int, int]
                ) -> Optional[Tuple[int, int]]:
        """u1·G + u2·Q with one interleaved Jacobian ladder (Shamir)."""
        u1 %= self.n
        u2 %= self.n
        jg = (self.g[0], self.g[1], 1)
        jq = (q[0], q[1], 1)
        jgq = self._jadd(jg, jq)
        acc = None
        for i in range(max(u1.bit_length(), u2.bit_length()) - 1, -1, -1):
            acc = self._jdbl(acc)
            sel = ((u1 >> i) & 1) | (((u2 >> i) & 1) << 1)
            if sel:
                acc = self._jadd(acc, (jg, jq, jgq)[sel - 1])
        return self._jaffine(acc)

    def on_curve(self, x: int, y: int) -> bool:
        return (y * y - (x * x * x + self.a * x + self.b)) % self.p == 0

    def compress(self, pt: Tuple[int, int]) -> bytes:
        x, y = pt
        return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")

    def decompress(self, blob: bytes) -> Optional[Tuple[int, int]]:
        """SEC1 compressed point → affine, None if malformed/off-curve."""
        if len(blob) != 33 or blob[0] not in (2, 3):
            return None
        x = int.from_bytes(blob[1:], "big")
        if x >= self.p:
            return None
        rhs = (x * x * x + self.a * x + self.b) % self.p
        y = pow(rhs, (self.p + 1) // 4, self.p)
        if y * y % self.p != rhs:
            return None
        if y & 1 != blob[0] & 1:
            y = self.p - y
        return (x, y)


SECP_HOST = HostCurve(w.SECP256K1_P, 0, w.SECP256K1_B, w.SECP256K1_N,
                      w.SECP256K1_GX, w.SECP256K1_GY)
SM2_HOST = HostCurve(w.SM2_P, w.SM2_A, w.SM2_B, w.SM2_N,
                     w.SM2_GX, w.SM2_GY)


def _det_nonce(sk: int, e: int, n: int, retry: int = 0) -> int:
    """Deterministic nonce: k = SM3(sk ‖ e ‖ retry ‖ ctr) chained until
    nonzero mod n (RFC 6979-shaped; exact RFC HMAC-DRBG construction not
    needed for the sim fleet).  `retry` is the signer's degenerate-r/s
    retry index and `ctr` absorbs zero-k draws — both live in their own
    hash-input fields, so a retried nonce can never collide with any
    message's first-try nonce (k is never reused across messages)."""
    ctr = 0
    while True:
        k = int.from_bytes(
            sm3_hash(sk.to_bytes(32, "big") + (e % 2**256).to_bytes(32, "big")
                     + retry.to_bytes(4, "big") + ctr.to_bytes(4, "big")),
            "big") % n
        if k:
            return k
        ctr += 1


# ---------------------------------------------------------------------------
# Device kernels (per curve, cached by (ops, nbits) via functools).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _verify_kernel(curve_name: str):
    """Jitted per-lane verify: R = u1·G + u2·Q; ok iff R ≠ ∞ and
    R.X == c·R.Z for one of two candidate x-lifts."""
    ops = {"secp256k1": w.SECP, "sm2": w.SM2}[curve_name]
    host = {"secp256k1": SECP_HOST, "sm2": SM2_HOST}[curve_name]
    f = ops.f
    gx = jnp.asarray(f.from_int(host.g[0]))[None]
    gy = jnp.asarray(f.from_int(host.g[1]))[None]

    @jax.jit
    def kernel(qx, qy, valid, u1_bits, u2_bits, c1, c2):
        g = ops.from_affine(gx.astype(jnp.int32), gy.astype(jnp.int32))
        q = ops.from_affine(qx, qy)
        # invalid lanes: zero scalars keep garbage coords out of the scan
        u1_bits = u1_bits * valid[:, None]
        u2_bits = u2_bits * valid[:, None]
        r = w.dual_scalar_mul_bits(ops, g, u1_bits, q, u2_bits)
        not_inf = ~f.is_zero(r.z)
        hit = (f.eq(r.x, f.mul(c1, r.z)) | f.eq(r.x, f.mul(c2, r.z)))
        return valid & not_inf & hit

    return kernel


# ---------------------------------------------------------------------------
# Providers.
# ---------------------------------------------------------------------------

class _EcdsaFamilyCrypto:
    """Shared provider shell: concat-aggregation QCs (like Ed25519Crypto
    — these schemes don't aggregate), device-batched verify_batch."""

    SIG_LEN = 64  # r ‖ s, 32 bytes each, big-endian
    curve_name = ""
    host: HostCurve

    def __init__(self, private_key: int, device_threshold: int = 64):
        host = self.host
        self._sk = private_key % host.n
        if self._sk == 0:
            raise CryptoError("zero private key")
        self._pk_pt = host.mul(self._sk, host.g)
        self._pk = host.compress(self._pk_pt)
        self._threshold = device_threshold
        # voter bytes → decompressed affine (or None if invalid), plus
        # device limb rows stacked for vectorized gathers.
        self._pk_index: Dict[bytes, int] = {}
        # Guards the read-check-append sequence below: the frontier runs
        # verify_batch calls via asyncio.to_thread (multiple in-flight
        # flushes), and two threads capturing `base` before either
        # concatenates would desynchronize index → row mapping (same
        # hazard TpuBlsCrypto._pk_lock covers).
        self._pk_lock = threading.Lock()
        f = {"secp256k1": w.FQ_SECP, "sm2": w.FQ_SM2}[self.curve_name]
        self._f = f
        self._pk_x = np.zeros((0, f.n), np.int32)
        self._pk_y = np.zeros((0, f.n), np.int32)

    # -- provider surface ---------------------------------------------------

    @property
    def pub_key(self) -> bytes:
        return self._pk

    def hash(self, data: bytes) -> bytes:
        return sm3_hash(data)

    def verify_signature(self, signature: bytes, hash32: bytes,
                         voter: bytes) -> bool:
        pt = self.host.decompress(bytes(voter))
        if pt is None:
            return False
        return self._host_verify(bytes(signature), bytes(hash32), pt)

    def aggregate_signatures(self, signatures: Sequence[bytes],
                             voters: Sequence[bytes]) -> bytes:
        if len(signatures) != len(voters):
            raise CryptoError(
                f"signatures x voters length mismatch "
                f"{len(signatures)} x {len(voters)}")
        for sig in signatures:
            if len(sig) != self.SIG_LEN:
                raise CryptoError(f"bad {self.curve_name} signature length")
        return b"".join(bytes(s) for s in signatures)

    def verify_aggregated_signature(self, agg_sig: bytes, hash32: bytes,
                                    voters: Sequence[bytes]) -> bool:
        if not voters:
            return False
        if len(agg_sig) != self.SIG_LEN * len(voters):
            return False
        sigs = [agg_sig[i * self.SIG_LEN:(i + 1) * self.SIG_LEN]
                for i in range(len(voters))]
        return all(self.verify_batch(sigs, [hash32] * len(voters), voters))

    # -- batched verification ------------------------------------------------

    def _host_verify_all(self, signatures, hashes, voters) -> List[bool]:
        """Per-lane host path — below-threshold route AND device-failure
        fallback.  One body: every path applies the same acceptance
        rule (low-s / candidate-lift checks live in _scalars_of)."""
        return [self.verify_signature(s, h, v)
                for s, h, v in zip(signatures, hashes, voters)]

    def verify_batch(self, signatures: Sequence[bytes],
                     hashes: Sequence[bytes],
                     voters: Sequence[bytes]) -> List[bool]:
        n = len(signatures)
        assert len(hashes) == n and len(voters) == n
        if n == 0:
            return []
        if n < self._threshold:
            return self._host_verify_all(signatures, hashes, voters)
        host, f = self.host, self._f
        rows = self._pk_rows_of(voters)

        valid = np.zeros(n, bool)
        u1 = [0] * n
        u2 = [0] * n
        c1 = [0] * n
        c2 = [0] * n
        for i in range(n):
            if rows[i] < 0:
                continue
            parsed = self._scalars_of(bytes(signatures[i]),
                                      bytes(hashes[i]))
            if parsed is None:
                continue
            u1[i], u2[i], c1[i], c2[i] = parsed
            valid[i] = True
        if not valid.any():
            return [False] * n

        size = _pad_to(n)
        pad_rows = np.zeros(size, np.int64)
        pad_rows[:n] = np.maximum(rows, 0)
        qx = self._pk_x[pad_rows]
        qy = self._pk_y[pad_rows]
        vmask = np.zeros(size, bool)
        vmask[:n] = valid

        def bits_of(vals):
            out = np.zeros((size, _SCALAR_BITS), np.int32)
            out[:n] = int_to_bits_msb_np(vals, _SCALAR_BITS)
            return jnp.asarray(out)

        def limbs_of(vals):
            out = np.zeros((size, f.n), np.int32)
            out[:n] = f.from_ints(vals)
            return jnp.asarray(out)

        # Device dispatch/readback failures degrade to the per-lane host
        # oracle (identical acceptance rule — low-s / candidate-lift
        # checks all live in _scalars_of, shared by both paths) instead
        # of raising out of the provider.
        try:
            ok = _verify_kernel(self.curve_name)(
                jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(vmask),
                bits_of(u1), bits_of(u2), limbs_of(c1), limbs_of(c2))
            return [bool(v) for v in np.asarray(ok)[:n]]
        except Exception as e:  # noqa: BLE001 — device path failed
            logger.warning("%s device batch failed (%s: %s); host "
                           "fallback", self.curve_name,
                           type(e).__name__, e)
            return self._host_verify_all(signatures, hashes, voters)

    # -- scheme internals ----------------------------------------------------

    def _scalars_of(self, sig: bytes, hash32: bytes
                    ) -> Optional[Tuple[int, int, int, int]]:
        """(u1, u2, c1, c2) for one lane, or None if the signature is
        structurally invalid.  c1/c2 are the candidate x-lifts (c2 == c1
        when c + n ≥ p)."""
        raise NotImplementedError

    def _host_verify(self, sig: bytes, hash32: bytes,
                     q: Tuple[int, int]) -> bool:
        host = self.host
        parsed = self._scalars_of(sig, hash32)
        if parsed is None:
            return False
        u1, u2, cand1, cand2 = parsed
        r_pt = host.mul_add(u1, u2, q)
        if r_pt is None:
            return False
        return r_pt[0] in (cand1, cand2)

    def _split_sig(self, sig: bytes) -> Optional[Tuple[int, int]]:
        if len(sig) != self.SIG_LEN:
            return None
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (1 <= r < self.host.n and 1 <= s < self.host.n):
            return None
        return r, s

    def _x_lifts(self, c: int) -> Tuple[int, int]:
        host = self.host
        lift2 = c + host.n
        return c, (lift2 if lift2 < host.p else c)

    # -- pubkey cache --------------------------------------------------------

    def _pk_rows_of(self, voters: Sequence[bytes]) -> np.ndarray:
        f = self._f
        with self._pk_lock:
            missing = []
            seen = set()
            for v in voters:
                vb = bytes(v)
                if vb not in self._pk_index and vb not in seen:
                    seen.add(vb)
                    missing.append(vb)
            if missing:
                base = self._pk_x.shape[0]
                xs, ys = [], []
                for j, vb in enumerate(missing):
                    pt = self.host.decompress(vb)
                    if pt is None:
                        self._pk_index[vb] = -1
                        xs.append(np.zeros(f.n, np.int32))
                        ys.append(np.zeros(f.n, np.int32))
                    else:
                        self._pk_index[vb] = base + j
                        xs.append(f.from_int(pt[0]))
                        ys.append(f.from_int(pt[1]))
                self._pk_x = np.concatenate([self._pk_x, np.stack(xs)],
                                            axis=0)
                self._pk_y = np.concatenate([self._pk_y, np.stack(ys)],
                                            axis=0)
            return np.fromiter((self._pk_index[bytes(v)] for v in voters),
                               np.int64, len(voters))


class Secp256k1Crypto(_EcdsaFamilyCrypto):
    """secp256k1 ECDSA over 32-byte hashes, low-s enforced both ways."""

    curve_name = "secp256k1"
    host = SECP_HOST

    def sign(self, hash32: bytes) -> bytes:
        host = self.host
        e = int.from_bytes(hash32, "big") % host.n
        for retry in range(2**31):
            k = _det_nonce(self._sk, e, host.n, retry)
            r_pt = host.mul(k, host.g)
            r = r_pt[0] % host.n
            s = (e + r * self._sk) * pow(k, host.n - 2, host.n) % host.n
            if r and s:
                if 2 * s > host.n:
                    s = host.n - s  # low-s normal form
                return r.to_bytes(32, "big") + s.to_bytes(32, "big")
        raise CryptoError("nonce derivation failed")  # unreachable

    def _scalars_of(self, sig, hash32):
        host = self.host
        rs = self._split_sig(sig)
        if rs is None:
            return None
        r, s = rs
        if 2 * s > host.n:
            return None  # low-s rule: one valid encoding per signature
        e = int.from_bytes(hash32, "big") % host.n
        w_inv = pow(s, host.n - 2, host.n)
        u1 = e * w_inv % host.n
        u2 = r * w_inv % host.n
        return (u1, u2) + self._x_lifts(r)


class Sm2Crypto(_EcdsaFamilyCrypto):
    """SM2 (GB/T 32918.2) over 32-byte hashes; e = int(hash32) directly
    (no Z_A pipeline — see module docstring)."""

    curve_name = "sm2"
    host = SM2_HOST

    def sign(self, hash32: bytes) -> bytes:
        host = self.host
        e = int.from_bytes(hash32, "big")
        inv_1sk = pow(1 + self._sk, host.n - 2, host.n)
        for retry in range(2**31):
            k = _det_nonce(self._sk, e, host.n, retry)
            x1 = host.mul(k, host.g)[0]
            r = (e + x1) % host.n
            if r == 0 or r + k == host.n:
                continue
            s = inv_1sk * (k - r * self._sk) % host.n
            if s == 0:
                continue
            return r.to_bytes(32, "big") + s.to_bytes(32, "big")
        raise CryptoError("nonce derivation failed")  # unreachable

    def _scalars_of(self, sig, hash32):
        host = self.host
        rs = self._split_sig(sig)
        if rs is None:
            return None
        r, s = rs
        t = (r + s) % host.n
        if t == 0:
            return None
        e = int.from_bytes(hash32, "big")
        # accept iff (e + x1) ≡ r (mod n)  ⇔  x1 ≡ r − e (mod n)
        c = (r - e) % host.n
        return (s, t) + self._x_lifts(c)
