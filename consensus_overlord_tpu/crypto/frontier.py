"""The batching frontier: coalesce concurrent signature verifications into
device-sized batches.

The reference verifies each inbound vote synchronously inside the engine's
message loop, one native blst call at a time (src/consensus.rs:397-416).
On TPU a single verification can't pay for a device dispatch — but a
consensus round delivers N votes near-simultaneously.  The frontier sits
at the inbound-network edge (the proc_network_msg path,
src/consensus.rs:210-262): each message's signature check becomes an
awaitable; requests that arrive within one linger window (or up to a max
batch) flush together through the provider's ``verify_batch`` — which for
TpuBlsCrypto is two MSMs on device + O(1) host pairings (SURVEY.md §7
"batching frontier" / hard part (c)).

Messages whose signatures fail are dropped at the frontier (the engine
then runs with ``inbound_verified=True`` and skips per-message verifies);
malformed input degrades to a False result, never an exception — the
log-and-drop posture of src/consensus.rs:220-260.
"""

from __future__ import annotations

import asyncio
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..core.sm3 import sm3_hash
from ..core.types import SignedChoke, SignedProposal, SignedVote
from ..obs.prof import annotate

logger = logging.getLogger("consensus_overlord_tpu.frontier")


def signature_claims(msg) -> Optional[Tuple[bytes, bytes, bytes]]:
    """(signature, hash32, voter) claimed by an inbound consensus message,
    or None for message types verified elsewhere (QCs carry aggregated
    signatures checked in the engine against the voter bitmap)."""
    if isinstance(msg, SignedProposal):
        return (msg.signature, sm3_hash(msg.proposal.encode()),
                msg.proposal.proposer)
    if isinstance(msg, SignedVote):
        return msg.signature, sm3_hash(msg.vote.encode()), msg.voter
    if isinstance(msg, SignedChoke):
        return msg.signature, sm3_hash(msg.choke.encode()), msg.address
    return None


@dataclass
class FrontierStats:
    requests: int = 0
    batches: int = 0
    max_batch: int = 0
    failures: int = 0

    @property
    def mean_batch(self) -> float:
        return self.requests / self.batches if self.batches else 0.0


class BatchingVerifier:
    """Coalesces `verify(sig, hash, voter)` awaitables into provider
    `verify_batch` calls.

    linger_s: how long the first request of a batch waits for company.
    max_batch: flush immediately at this size (matches the provider's
    padded batch ladder so device kernels stay shape-stable).
    metrics: optional obs.Metrics — every flush observes batch size,
    per-request queue wait, padded-batch occupancy, and dispatch/resolve
    phase latency; failures count by message type.  None = no overhead.
    """

    def __init__(self, provider, max_batch: int = 1024,
                 linger_s: float = 0.002, metrics=None):
        self._provider = provider
        self._max_batch = max_batch
        self._linger = linger_s
        self._metrics = metrics
        #: (sig, hash32, voter, future, msg_type, enqueue_ts)
        self._pending: List[Tuple] = []
        self._flush_task: Optional[asyncio.Task] = None
        # asyncio holds only weak refs to tasks; in-flight batch tasks must
        # be pinned or GC can collect one mid-verify, hanging every waiter.
        self._inflight: set = set()
        # One dedicated dispatch worker: device dispatches (which may
        # block on a cold jit compile — minutes for a new batch shape —
        # or on H2D transfers over a remote PJRT link) run OFF the event
        # loop, and the single worker keeps dispatch order FIFO across
        # flushes so pipelining stays deterministic.
        self._dispatcher = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="frontier-dispatch")
        self.stats = FrontierStats()

    async def verify(self, signature: bytes, hash32: bytes,
                     voter: bytes, msg_type: str = "raw") -> bool:
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((bytes(signature), bytes(hash32), bytes(voter),
                              fut, msg_type, time.perf_counter()))
        self.stats.requests += 1
        if len(self._pending) >= self._max_batch:
            self._flush_now("max_batch")
        elif self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_running_loop().create_task(
                self._linger_then_flush())
        return await fut

    async def verify_msg(self, msg) -> bool:
        """Verify a decoded consensus message's signature claim; True for
        message types with no frontier-checkable signature."""
        claims = signature_claims(msg)
        if claims is None:
            return True
        return await self.verify(*claims, msg_type=type(msg).__name__)

    async def verify_aggregated(self, agg_sig: bytes, hash32: bytes,
                                voters) -> bool:
        """QC aggregate verification off the event loop: dispatch through
        the same single ordered worker as batch flushes (device FIFO
        stays intact), block only in a resolver thread.  The engine
        awaits this from _verify_qc so a ≥1024-voter QC check never
        stalls consensus timers on a ~200 ms device round-trip."""
        dispatch = getattr(self._provider, "verify_aggregated_async", None)
        try:
            if dispatch is None:
                return await asyncio.to_thread(
                    self._provider.verify_aggregated_signature,
                    agg_sig, hash32, voters)
            return await self._via_dispatcher(dispatch, agg_sig, hash32,
                                              voters)
        except Exception:  # noqa: BLE001 — malformed input is never fatal
            logger.exception("frontier QC verification errored")
            return False

    async def aggregate(self, signatures, voters) -> bytes:
        """QC signature aggregation off the event loop (leader path).
        Raises CryptoError on invalid input, like the sync form."""
        dispatch = getattr(self._provider, "aggregate_signatures_async",
                           None)
        if dispatch is None:
            return await asyncio.to_thread(
                self._provider.aggregate_signatures, signatures, voters)
        return await self._via_dispatcher(dispatch, signatures, voters)

    async def _via_dispatcher(self, dispatch, *args):
        """dispatch(*args) on the ordered worker → resolve() in a second
        thread (overlaps the dispatch→readback round-trip with device
        compute, same pipeline as _run_batch)."""
        loop = asyncio.get_running_loop()
        resolver = await loop.run_in_executor(self._dispatcher, dispatch,
                                              *args)
        return await asyncio.to_thread(resolver)

    def close(self) -> None:
        """Release the dispatch worker thread (engine/sim teardown).
        Still-pending requests are flushed first (reason="shutdown") so
        their futures resolve instead of hanging their awaiters — only
        possible from a running event loop (the normal teardown path).
        The worker shuts down only after in-flight batch tasks (incl. a
        shutdown flush) have dispatched through it — shutting it down
        eagerly would bounce those batches onto the per-signature host
        re-verify fallback (RuntimeError from run_in_executor)."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:  # no loop: nothing can await those futures
            loop = None
            self._pending = []
        if self._pending:
            self._flush_now("shutdown")
        if loop is not None and self._inflight:
            dispatcher = self._dispatcher

            async def _drain_then_release(tasks):
                try:
                    await asyncio.gather(*tasks, return_exceptions=True)
                finally:
                    # Loop teardown can cancel this task mid-gather; the
                    # worker thread must be released regardless or each
                    # closed frontier leaks one non-daemon thread.
                    dispatcher.shutdown(wait=False)

            # Pinned in _inflight: asyncio holds only weak task refs
            # (see __init__) — an unpinned drain task can be GC'd
            # mid-await, leaking the worker thread.
            task = loop.create_task(_drain_then_release(
                list(self._inflight)))
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)
        else:
            self._dispatcher.shutdown(wait=False)

    async def _linger_then_flush(self) -> None:
        await asyncio.sleep(self._linger)
        self._flush_now("linger")

    def _flush_now(self, reason: str) -> None:
        batch, self._pending = self._pending, []
        if not batch:
            return
        if self._metrics is not None:
            # Why the batch left the frontier: linger-expired vs
            # max-batch vs shutdown drain — without this the queue-wait
            # histogram is uninterpretable (a long wait is EXPECTED
            # under linger flushes, a red flag under max-batch ones).
            self._metrics.frontier_flush_reason.labels(reason=reason).inc()
        if self._flush_task is not None and not self._flush_task.done():
            self._flush_task.cancel()
        self._flush_task = None
        task = asyncio.get_running_loop().create_task(self._run_batch(batch))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _run_batch(self, batch) -> None:
        sigs = [b[0] for b in batch]
        hashes = [b[1] for b in batch]
        voters = [b[2] for b in batch]
        m = self._metrics
        if m is not None:
            # Batch size only; padded-rung occupancy is observed by the
            # provider at host-prep time (crypto/tpu_provider.py), where
            # the pad sizes are actually computed — one source of truth
            # across the fused/split dispatch plans.
            m.frontier_batch_size.observe(len(batch))
        try:
            verify_async = getattr(self._provider, "verify_batch_async",
                                   None)
            if verify_async is not None:
                # Dispatch through the single ordered worker (off-loop:
                # a cold compile or H2D transfer never stalls consensus
                # timers), then block only for the readback in a second
                # thread — consecutive flushes overlap the ~200 ms
                # dispatch→readback round-trip of a remote PJRT link
                # with device compute.
                loop = asyncio.get_running_loop()
                t0 = time.perf_counter()
                with annotate("frontier.flush"):
                    resolver = await loop.run_in_executor(
                        self._dispatcher, verify_async, sigs, hashes,
                        voters)
                t1 = time.perf_counter()
                results = await asyncio.to_thread(resolver)
                if m is not None:
                    # frontier_* phases are wrappers AROUND the provider's
                    # prep/dispatch/readback/pairing phases (they include
                    # executor queueing), distinct labels so the series
                    # compose instead of double-counting.
                    t2 = time.perf_counter()
                    m.crypto_dispatch_ms.labels(
                        phase="frontier_dispatch").observe(
                        (t1 - t0) * 1000.0)
                    m.crypto_dispatch_ms.labels(
                        phase="frontier_resolve").observe(
                        (t2 - t1) * 1000.0)
            else:
                # Device dispatch blocks; keep the event loop live.
                t0 = time.perf_counter()
                results = await asyncio.to_thread(
                    self._provider.verify_batch, sigs, hashes, voters)
                if m is not None:
                    m.crypto_dispatch_ms.labels(
                        phase="frontier_resolve").observe(
                        (time.perf_counter() - t0) * 1000.0)
            errored = False
        except Exception:  # noqa: BLE001 — malformed input is never fatal
            # A provider whose device path died mid-batch (and that has
            # no internal breaker/fallback of its own): re-verify every
            # lane on the host oracle — consensus keeps making progress
            # on exact verdicts instead of dropping a whole batch of
            # honest votes as if they were forged.
            logger.exception(
                "frontier batch verification errored; host re-verify")
            if m is not None:
                m.host_fallbacks.labels(path="frontier_reverify").inc()
            try:
                results = await asyncio.to_thread(
                    lambda: [self._provider.verify_signature(s, h, v)
                             for s, h, v in zip(sigs, hashes, voters)])
                errored = False
            except Exception:  # noqa: BLE001 — even the oracle failed
                logger.exception("frontier host re-verify errored")
                results = [False] * len(batch)
                errored = True
                if m is not None:
                    # One event under its own label: an infra error must
                    # not masquerade as a per-message signature attack.
                    m.frontier_verify_failures.labels(
                        msg_type="batch_error").inc()
        self.stats.batches += 1
        self.stats.max_batch = max(self.stats.max_batch, len(batch))
        now = time.perf_counter()
        for (_, _, _, fut, msg_type, t_enq), ok in zip(batch, results):
            if not ok:
                self.stats.failures += 1
                if m is not None and not errored:
                    m.frontier_verify_failures.labels(
                        msg_type=msg_type).inc()
            if m is not None:
                m.frontier_queue_wait_ms.observe((now - t_enq) * 1000.0)
            if not fut.done():
                fut.set_result(bool(ok))
