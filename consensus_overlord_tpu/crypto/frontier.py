"""The batching frontier: coalesce concurrent signature verifications into
device-sized batches.

The reference verifies each inbound vote synchronously inside the engine's
message loop, one native blst call at a time (src/consensus.rs:397-416).
On TPU a single verification can't pay for a device dispatch — but a
consensus round delivers N votes near-simultaneously.  The frontier sits
at the inbound-network edge (the proc_network_msg path,
src/consensus.rs:210-262): each message's signature check becomes an
awaitable; requests that arrive within one linger window (or up to a max
batch) flush together through the provider's ``verify_batch`` — which for
TpuBlsCrypto is two MSMs on device + O(1) host pairings (SURVEY.md §7
"batching frontier" / hard part (c)).

Messages whose signatures fail are dropped at the frontier (the engine
then runs with ``inbound_verified=True`` and skips per-message verifies);
malformed input degrades to a False result, never an exception — the
log-and-drop posture of src/consensus.rs:220-260.

Since the multi-tenant refactor (crypto/tenancy.py) the batching core is
``SharedFrontier`` and ``BatchingVerifier`` is its single-tenant shape: a
``TenantLane`` over a core it owns.  Two consequences for the classic
single-engine path:

  * outstanding work is now BOUNDED (``max_pending`` counts queued AND
    composed-but-unresolved requests): a stalled device no longer
    accumulates verifies without limit — overflow sheds to the
    provider's host-oracle ``verify_signature`` (the PR 2 breaker
    fallback twin) with exact verdicts, counted in
    ``frontier_admission_sheds_total{tenant="default"}``;
  * proposal verifies ride the critical priority class and drain before
    gossip within each flush (``priority_lanes=False`` restores strict
    FIFO).

``signature_claims`` and ``FrontierStats`` live in crypto/tenancy.py now
and are re-exported here for compatibility.
"""

from __future__ import annotations

from .tenancy import (  # noqa: F401 — compatibility re-exports
    DEFAULT_QUEUE_BOUND,
    FrontierStats,
    SharedFrontier,
    TenantLane,
    TenantStats,
    signature_claims,
)

__all__ = [
    "BatchingVerifier",
    "DEFAULT_QUEUE_BOUND",
    "FrontierStats",
    "SharedFrontier",
    "TenantLane",
    "TenantStats",
    "signature_claims",
]


class BatchingVerifier(TenantLane):
    """Coalesces `verify(sig, hash, voter)` awaitables into provider
    `verify_batch` calls — the single-tenant lane over a SharedFrontier
    core this instance owns (and closes).

    linger_s: how long the first request of a batch waits for company.
    max_batch: flush immediately at this size (matches the provider's
    padded batch ladder so device kernels stay shape-stable).
    max_pending: outstanding-work bound (queued + composed-but-
    unresolved); arrivals over it shed to the provider's host-oracle
    verify with exact verdicts (a stalled device degrades throughput,
    never correctness or memory).
    metrics: optional obs.Metrics — every flush observes batch size,
    per-request queue wait, padded-batch occupancy, and dispatch/resolve
    phase latency; failures count by message type.  None = no overhead.
    """

    def __init__(self, provider, max_batch: int = 1024,
                 linger_s: float = 0.002, metrics=None,
                 max_pending: int = DEFAULT_QUEUE_BOUND,
                 tenant_id: str = "default", weight: int = 1,
                 priority_lanes: bool = True, recorder=None):
        if max_pending < max_batch:
            # The config layer rejects this too; direct constructions
            # (bench scripts, sim harness) must hit the same wall.  A
            # multi-tenant lane MAY be bounded below the shared
            # max_batch (batches compose across tenants), but this
            # lane is the core's only tenant: a bound below one batch
            # sheds traffic a single flush could have carried, and the
            # size-flush trigger could never fire.
            raise ValueError(
                f"max_pending ({max_pending}) must be >= max_batch "
                f"({max_batch}) for a single-tenant frontier")
        core = SharedFrontier(provider, max_batch=max_batch,
                              linger_s=linger_s, metrics=metrics,
                              recorder=recorder)
        super().__init__(core, tenant_id, weight=weight,
                         queue_bound=max_pending,
                         priority_lanes=priority_lanes)
        core.adopt(self)

    @property
    def stats(self) -> FrontierStats:
        """The legacy whole-frontier counters (requests / batches /
        mean_batch / max_batch / failures) — what /statusz "frontier"
        and the bench scripts read.  Per-tenant counters (sheds, queue
        waits) live on ``tenant_stats``."""
        return self._core.stats

    @property
    def core(self) -> SharedFrontier:
        return self._core

    def close(self) -> None:
        """This lane owns its core: release the dispatch worker thread
        (engine/sim teardown), draining pending requests first."""
        self._core.close()
