#!/usr/bin/env python3
"""grpc-health-probe equivalent (reference Dockerfile:16): exit 0 iff the
consensus service's Health.check answers SERVING."""
import sys

import grpc

from consensus_overlord_tpu.service.pb import pb2  # noqa: E402


def main() -> int:
    addr = sys.argv[1] if len(sys.argv) > 1 else "localhost:50001"
    channel = grpc.insecure_channel(addr)
    stub = channel.unary_unary(
        "/consensus_overlord_tpu.Health/Check",
        request_serializer=pb2.HealthCheckRequest.SerializeToString,
        response_deserializer=pb2.HealthCheckResponse.FromString)
    try:
        resp = stub(pb2.HealthCheckRequest(), timeout=3)
    except grpc.RpcError as e:
        print(f"probe failed: {e.code()}", file=sys.stderr)
        return 1
    ok = resp.status == pb2.HealthCheckResponse.SERVING
    print("SERVING" if ok else "NOT_SERVING")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
