"""Core layer tests: RLP, SM3, wire types, voter bitmaps."""

import pytest

from consensus_overlord_tpu.core import rlp, sm3, bitmap
from consensus_overlord_tpu.core.types import (
    AggregatedSignature,
    AggregatedVote,
    Choke,
    DurationConfig,
    Node,
    Proof,
    Proposal,
    SignedChoke,
    SignedProposal,
    SignedVote,
    Vote,
    VoteType,
    validator_to_origin,
    validators_to_nodes,
)


class TestRlp:
    # Classic RLP reference vectors (yellow-paper / ethereum wiki examples).
    VECTORS = [
        (b"dog", bytes([0x83]) + b"dog"),
        ([b"cat", b"dog"], bytes([0xC8, 0x83]) + b"cat" + bytes([0x83]) + b"dog"),
        (b"", bytes([0x80])),
        ([], bytes([0xC0])),
        (b"\x0f", bytes([0x0F])),
        (b"\x04\x00", bytes([0x82, 0x04, 0x00])),
        (
            [[], [[]], [[], [[]]]],
            bytes([0xC7, 0xC0, 0xC1, 0xC0, 0xC3, 0xC0, 0xC1, 0xC0]),
        ),
        (
            b"Lorem ipsum dolor sit amet, consectetur adipisicing elit",
            bytes([0xB8, 0x38]) + b"Lorem ipsum dolor sit amet, consectetur adipisicing elit",
        ),
    ]

    def test_vectors(self):
        for item, expected in self.VECTORS:
            assert rlp.encode(item) == expected
            assert rlp.decode(expected) == item

    def test_int_encoding(self):
        assert rlp.encode(0) == bytes([0x80])
        assert rlp.encode(15) == bytes([0x0F])
        assert rlp.encode(1024) == bytes([0x82, 0x04, 0x00])

    def test_long_list_roundtrip(self):
        item = [b"x" * 100, [b"y" * 300, b"z"], b""]
        assert rlp.decode(rlp.encode(item)) == item

    def test_reject_trailing(self):
        with pytest.raises(rlp.RlpError):
            rlp.decode(rlp.encode(b"dog") + b"\x00")

    def test_reject_noncanonical(self):
        with pytest.raises(rlp.RlpError):
            rlp.decode(bytes([0x81, 0x05]))  # single byte < 0x80 must be literal

    def test_deep_nesting_rejected(self):
        with pytest.raises(rlp.RlpError):
            rlp.decode(b"\xc1" * 5000 + b"\xc0")


class TestSm3:
    # Both the active sm3_hash (possibly OpenSSL-backed) and the from-scratch
    # pure-Python fallback must match the standard vectors.
    IMPLS = [sm3.sm3_hash, sm3._sm3_hash_py]

    def test_abc(self):
        # GB/T 32905-2016 appendix A.1 example vector.
        for impl in self.IMPLS:
            assert (
                impl(b"abc").hex()
                == "66c7f0f462eeedd9d1f2d46bdc10e4e24167c4875cf2f7a2297da02b8f4ba8e0"
            )

    def test_abcd_x16(self):
        # GB/T 32905-2016 appendix A.2 example vector (512-bit message).
        for impl in self.IMPLS:
            assert (
                impl(b"abcd" * 16).hex()
                == "debe9ff92275b8a138604889c18e5a4d6fdb70e5387e5765293dcba39c0c5732"
            )

    def test_empty(self):
        for impl in self.IMPLS:
            assert (
                impl(b"").hex()
                == "1ab21d8355cfa17f8e61194831e81a8f22bec8c728fefb747ed035eb5082aa2b"
            )

    def test_fallback_matches_active_on_long_input(self):
        data = bytes(range(256)) * 33  # multi-block, unaligned tail
        assert sm3.sm3_hash(data) == sm3._sm3_hash_py(data)

    def test_width(self):
        assert len(sm3.sm3_hash(b"anything")) == sm3.HASH_BYTES_LEN == 32


def _sample_agg_vote(height=7, round_=2):
    return AggregatedVote(
        signature=AggregatedSignature(signature=b"\x01" * 96, address_bitmap=b"\xE0"),
        vote_type=VoteType.PRECOMMIT,
        height=height,
        round=round_,
        block_hash=b"\xAB" * 32,
        leader=b"\x11" * 48,
    )


class TestWireTypes:
    def test_vote_roundtrip(self):
        v = Vote(5, 1, VoteType.PREVOTE, b"\x22" * 32)
        assert Vote.from_rlp(rlp.decode(v.encode())) == v

    def test_signed_vote_roundtrip(self):
        sv = SignedVote(b"\x33" * 48, b"\x44" * 96, Vote(9, 0, VoteType.PRECOMMIT, b"\x55" * 32))
        assert SignedVote.decode(sv.encode()) == sv

    def test_aggregated_vote_roundtrip(self):
        av = _sample_agg_vote()
        assert AggregatedVote.decode(av.encode()) == av
        assert av.to_vote() == Vote(7, 2, VoteType.PRECOMMIT, b"\xAB" * 32)

    def test_proposal_roundtrip_with_and_without_lock(self):
        for lock in (None, _sample_agg_vote()):
            p = Proposal(3, 1, b"block-bytes", b"\x77" * 32, lock, b"\x88" * 48)
            sp = SignedProposal(p, b"\x99" * 96)
            assert SignedProposal.decode(sp.encode()) == sp

    def test_choke_roundtrip(self):
        sc = SignedChoke(b"\xAA" * 96, b"\xBB" * 48, Choke(11, 4))
        assert SignedChoke.decode(sc.encode()) == sc

    def test_proof_roundtrip(self):
        pf = Proof(100, 0, b"\xCC" * 32,
                   AggregatedSignature(b"\xDD" * 96, b"\xF0"))
        assert Proof.decode(pf.encode()) == pf

    def test_duration_config_defaults(self):
        # Reference src/util.rs:90: DurationConfig::new(15, 10, 10, 7).
        dc = DurationConfig()
        assert (dc.propose_ratio, dc.prevote_ratio, dc.precommit_ratio,
                dc.brake_ratio) == (15, 10, 10, 7)
        assert DurationConfig.from_rlp(
            [rlp.encode_int(x) for x in (15, 10, 10, 7)]) == dc

    def test_wrong_arity_rejected(self):
        sv = SignedVote(b"\x01" * 48, b"\x02" * 96,
                        Vote(1, 0, VoteType.PREVOTE, b"\x03" * 32))
        item = rlp.decode(sv.encode())
        item.append(b"extra")
        with pytest.raises(rlp.RlpError):
            SignedVote.from_rlp(item)

    def test_wrong_field_kind_rejected(self):
        # An RLP empty list where a byte string belongs must not decode to b"".
        sv = SignedVote(b"", b"\x02" * 96,
                        Vote(1, 0, VoteType.PREVOTE, b"\x03" * 32))
        item = rlp.decode(sv.encode())
        item[0] = []
        with pytest.raises(rlp.RlpError):
            SignedVote.from_rlp(item)

    def test_invalid_vote_type_raises_rlp_error(self):
        v = Vote(1, 0, VoteType.PREVOTE, b"\x03" * 32)
        item = rlp.decode(v.encode())
        item[2] = rlp.encode_int(9)
        with pytest.raises(rlp.RlpError):
            Vote.from_rlp(item)

    def test_lock_byte_string_form_rejected(self):
        # An absent proposal lock must be exactly the empty list.
        p = Proposal(1, 0, b"c", b"\xaa" * 32, None, b"\xbb" * 48)
        item = rlp.decode(p.encode())
        item[4] = b""
        with pytest.raises(rlp.RlpError):
            Proposal.from_rlp(item)

    def test_validator_helpers(self):
        vals = [b"\x01" * 48, b"\x02" * 48]
        nodes = validators_to_nodes(vals)
        assert all(n.propose_weight == 1 and n.vote_weight == 1 for n in nodes)
        # Reference src/util.rs:93-97: origin = BE u64 of first 8 bytes.
        assert validator_to_origin(b"\x00" * 7 + b"\x2A" + b"\xFF" * 40) == 42


class TestBitmap:
    def test_roundtrip(self):
        nodes = [Node(bytes([i]) * 48) for i in (5, 1, 9, 3, 7, 2, 8, 6, 4)]
        voters = [nodes[0].address, nodes[2].address, nodes[8].address]
        bm = bitmap.build_bitmap(nodes, voters)
        assert len(bm) == 2  # 9 authorities -> 2 bytes
        extracted = bitmap.extract_voters(nodes, bm)
        assert sorted(extracted) == sorted(voters)
        # Extraction order is sorted-authority order.
        assert extracted == sorted(extracted)

    def test_unknown_voter_rejected(self):
        nodes = [Node(b"\x01" * 48)]
        with pytest.raises(ValueError):
            bitmap.build_bitmap(nodes, [b"\x02" * 48])

    def test_wrong_length_rejected(self):
        nodes = [Node(b"\x01" * 48)]
        with pytest.raises(ValueError):
            bitmap.extract_voters(nodes, b"\x00\x00")

    def test_padding_bits_rejected(self):
        # Set bits beyond the authority count would make proof bytes malleable.
        nodes = [Node(bytes([i]) * 48) for i in range(9)]
        with pytest.raises(ValueError):
            bitmap.extract_voters(nodes, b"\x80\x7f")

