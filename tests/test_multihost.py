"""Multi-host (DCN) init: single-process degenerate path, global mesh,
and a REAL two-process coordinator run.

The fast tests pin the contract the launcher relies on (no-coordinator →
clean single-process fallback; the global mesh spans every (virtual)
device in jax.devices() order).  The slow test actually spawns two OS
processes that join one jax.distributed runtime over a local coordinator
— the DCN handshake a single process can never cover."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from consensus_overlord_tpu.parallel import (  # noqa: E402
    global_mesh, init_multihost, make_mesh)


def _clean_subprocess_env():
    """Env for worker subprocesses, stripped of everything that poisons
    backend selection: the forced device count, the platform pin, and
    the TPU-relay plugin trigger (its sitecustomize hook initializes a
    PJRT backend at interpreter startup)."""
    return {k: v for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS",
                         "PALLAS_AXON_POOL_IPS")}


def test_init_without_coordinator_is_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert init_multihost() is False
    assert jax.process_count() == 1


def test_global_mesh_spans_all_devices():
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert list(mesh.axis_names) == ["lanes"]
    # host-major order: identical to jax.devices() (the documented
    # ICI-first combine layout)
    assert list(mesh.devices.ravel()) == list(jax.devices())


def test_global_mesh_matches_make_mesh_shape():
    m1, m2 = global_mesh(), make_mesh()
    assert m1.devices.size == m2.devices.size


def test_provider_verdicts_identical_over_global_mesh():
    """Single-process degenerate equivalence: a provider built over
    global_mesh() (the multi-host launcher's mesh, host-major) must
    return the same verify_batch verdicts — device pairing included —
    as one over make_mesh().  With one process the two meshes contain
    the same devices, so any divergence is a sharding-layout bug in the
    kernel set, not a DCN effect."""
    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto import bls12381 as oracle
    from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto

    batch = 16
    h = sm3_hash(b"global-mesh-degenerate")
    sks = [7000 + 13 * i for i in range(batch)]
    sigs = [oracle.sign(sk, h) for sk in sks]
    pks = [oracle.sk_to_pk(sk) for sk in sks]
    sigs[5] = oracle.sign(sks[5], sm3_hash(b"tampered"))

    verdicts = []
    for mesh in (global_mesh(), make_mesh()):
        provider = TpuBlsCrypto(0xD1CE, device_threshold=1, mesh=mesh,
                                device_pairing=True)
        provider.update_pubkeys(pks)
        got = provider.verify_batch(sigs, [h] * batch, pks)
        assert provider.pairing_host_fallbacks == 0
        verdicts.append(got)
    assert verdicts[0] == verdicts[1] == [i != 5 for i in range(batch)]


@pytest.mark.slow
def test_two_process_dcn_verify_round():
    """Two OS processes × 2 virtual CPU devices join one
    jax.distributed runtime (the DCN analog executable here), build the
    host-major global mesh, and run the production sharded verify-round
    kernel over a batch spanning both processes — each asserting the
    replicated MSM aggregates against the host oracle
    (tests/dcn_worker.py).  Exercises the real multi-process
    coordinator path that single-process tests cannot."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "dcn_worker.py")
    # jax.distributed.initialize must precede any backend init — hence
    # the stripped env.
    env = _clean_subprocess_env()
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), "2", coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=1800)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
        assert "DCN-OK" in out


@pytest.mark.slow
def test_dryrun_multichip_16_devices():
    """The 16-device mesh certification behind BASELINE.md's north-star
    re-scope (<50 ms / 10k votes ⇒ 0.48 s / 16 chips ≈ 30 ms): the
    budget math must rest on a mesh SHAPE that has actually compiled and
    executed the production provider end-to-end, not only the driver's
    8-device artifact.  Runs __graft_entry__.dryrun_multichip(16) in a
    fresh process (device count is fixed at backend init, so the
    conftest's 8-device backend can't be resized in-process).
    Measured r5: 115.6 s cold on the 2-vCPU dev host."""
    import subprocess
    import sys

    env = _clean_subprocess_env()
    proc = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; "
         "dryrun_multichip(16); print('DRYRUN16-OK')"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, timeout=1800)
    assert proc.returncode == 0, f"dryrun(16) failed:\n{proc.stdout[-4000:]}"
    assert "DRYRUN16-OK" in proc.stdout
