"""Multi-host (DCN) init: single-process degenerate path + global mesh.

The real multi-process path needs a coordinator across machines; the CI
environment has one host, so these tests pin the contract the launcher
relies on: no-coordinator → clean single-process fallback, and the
global mesh spans every (virtual) device in jax.devices() order."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from consensus_overlord_tpu.parallel import (  # noqa: E402
    global_mesh, init_multihost, make_mesh)


def test_init_without_coordinator_is_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert init_multihost() is False
    assert jax.process_count() == 1


def test_global_mesh_spans_all_devices():
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert list(mesh.axis_names) == ["lanes"]
    # host-major order: identical to jax.devices() (the documented
    # ICI-first combine layout)
    assert list(mesh.devices.ravel()) == list(jax.devices())


def test_global_mesh_matches_make_mesh_shape():
    m1, m2 = global_mesh(), make_mesh()
    assert m1.devices.size == m2.devices.size
