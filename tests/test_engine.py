"""SMR engine + sim harness tests: the multi-node-without-a-cluster strategy
SURVEY.md §4 prescribes.  Safety: no two blocks per height (asserted inside
SimController on every commit).  Liveness: progress under leader isolation,
partitions (after healing), and message loss."""

import asyncio

import pytest

from consensus_overlord_tpu.core.bitmap import extract_voters
from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.core.types import Proof, Vote, VoteType
from consensus_overlord_tpu.crypto.provider import CpuBlsCrypto, sim_crypto
from consensus_overlord_tpu.engine.smr import quorum_weight
from consensus_overlord_tpu.engine.wal import FileWal, MemoryWal
from consensus_overlord_tpu.sim import SimNetwork
from consensus_overlord_tpu.sim.harness import SimNode


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_quorum_weight():
    assert quorum_weight(4) == 3   # f=1: need 3 of 4
    assert quorum_weight(3) == 3   # 3 nodes: need all... (2*3//3+1)
    assert quorum_weight(10) == 7
    assert quorum_weight(3 * 333 + 1) == 667


class TestHappyPath:
    def test_four_validators_commit(self):
        async def main():
            net = SimNetwork(n_validators=4, block_interval_ms=50)
            net.start(init_height=1)
            await net.run_until_height(5)
            # Every height has exactly one block; all nodes agree.
            assert sorted(net.controller.chain) == [1, 2, 3, 4, 5]
            await net.stop()
        run(main())

    def test_single_validator(self):
        async def main():
            net = SimNetwork(n_validators=1, block_interval_ms=20)
            net.start(init_height=1)
            await net.run_until_height(3)
            await net.stop()
        run(main())

    def test_proof_audit(self):
        """Committed proofs must pass the check_block audit (reference
        src/consensus.rs:144-207): reconstruct the precommit vote, extract
        voters from the bitmap, verify the aggregated signature."""
        async def main():
            net = SimNetwork(n_validators=4, block_interval_ms=50)
            net.start(init_height=1)
            await net.run_until_height(3)
            await net.stop()
            crypto = net.nodes[0].crypto
            authority = net.controller.authority_list()
            for height, content in net.controller.chain.items():
                proof = Proof.decode(net.controller.proofs[height])
                assert proof.height == height
                assert proof.block_hash == sm3_hash(content)
                vote = Vote(proof.height, proof.round, VoteType.PRECOMMIT,
                            proof.block_hash)
                voters = extract_voters(authority,
                                        proof.signature.address_bitmap)
                assert quorum_weight(len(authority)) <= len(voters)
                assert crypto.verify_aggregated_signature(
                    proof.signature.signature, sm3_hash(vote.encode()), voters)
        run(main())


class TestFaults:
    def test_leader_isolated_view_change(self):
        """Isolating the round leader must trigger choke-quorum view change
        and commit under the next leader (reference liveness machinery,
        src/consensus.rs:247-258, 777-779)."""
        async def main():
            net = SimNetwork(n_validators=4, block_interval_ms=50)
            net.start(init_height=1)
            await net.run_until_height(1)
            # Isolate the leader of the next height's round 0.
            height = net.controller.latest_height + 1
            leader = net.nodes[0].engine.leader(height, 0)
            others = {n.name for n in net.nodes if n.name != leader}
            net.router.set_partition(others, {leader})
            await net.run_until_height(height, timeout=20)
            net.router.set_partition()
            assert any(a.view_changes for a in
                       (n.adapter for n in net.nodes))
            await net.stop()
        run(main())

    def test_partition_blocks_then_heals(self):
        """A 2+2 split must make no progress (safety); healing restores
        liveness."""
        async def main():
            net = SimNetwork(n_validators=4, block_interval_ms=50)
            net.start(init_height=1)
            await net.run_until_height(2)
            base = net.controller.latest_height
            group_a = {net.nodes[0].name, net.nodes[1].name}
            group_b = {net.nodes[2].name, net.nodes[3].name}
            net.router.set_partition(group_a, group_b)
            await asyncio.sleep(1.0)
            assert net.controller.latest_height <= base + 1  # no quorum → stall
            stalled = net.controller.latest_height
            net.router.set_partition()
            await net.run_until_height(stalled + 2, timeout=20)
            await net.stop()
        run(main())

    def test_lossy_network(self):
        """20% message drop + jitter: chokes/view-changes plus the controller
        status push keep the chain moving."""
        async def main():
            net = SimNetwork(n_validators=4, block_interval_ms=50, seed=7,
                             drop_rate=0.2, delay_range=(0.0, 0.02))
            net.start(init_height=1)
            await net.run_until_height(4, timeout=45)
            await net.stop()
        run(main())

    def test_crash_recovery_with_file_wal(self, tmp_path):
        """Stop a node, restart it from its WAL + the controller height
        (the reference's two-level resume, SURVEY.md §5 checkpoint/resume);
        it must rejoin and the fleet keep committing."""
        async def main():
            net = SimNetwork(n_validators=4, block_interval_ms=50)
            # Give node 0 a file WAL.
            crashed = net.nodes[0]
            crashed.wal = FileWal(str(tmp_path / "wal0"))
            crashed.engine.wal = crashed.wal
            net.start(init_height=1)
            await net.run_until_height(2)
            await crashed.stop()
            # Fleet of 3 (quorum of 4) keeps going while node 0 is down.
            await net.run_until_height(net.controller.latest_height + 2)
            # Restart node 0 from its WAL; init height from the controller
            # (ping_controller equivalent, reference src/consensus.rs:264-292).
            revived = SimNode(crashed.crypto, net.router, net.controller,
                              wal=FileWal(str(tmp_path / "wal0")))
            net.nodes[0] = revived
            revived.start(net.controller.latest_height + 1,
                          net.controller.block_interval_ms,
                          net.controller.authority_list())
            target = net.controller.latest_height + 3
            await net.run_until_height(target, timeout=30)
            # The revived node must be participating again (committing).
            await asyncio.sleep(0.3)
            revived_heights = [h for (node, h, _) in
                               net.controller.commit_log
                               if node == revived.name]
            assert revived_heights and max(revived_heights) > target - 3
            await net.stop()
        run(main())


class TestCommitRetry:
    def test_failed_commit_is_retried_from_timer(self):
        """A failed adapter.commit must be re-driven by the engine itself
        (reference Brain::commit retry posture, src/consensus.rs:594-657) —
        not wait for a duplicate QC broadcast or a controller resync.  A
        1-validator net produces each QC exactly once, so without the
        retry timer the first two failures would wedge the chain."""
        async def main():
            net = SimNetwork(n_validators=1, block_interval_ms=20)
            adapter = net.nodes[0].adapter
            real_commit = adapter.commit
            failures = {"left": 2, "seen": 0}

            async def flaky_commit(height, commit):
                failures["seen"] += 1
                if failures["left"] > 0:
                    failures["left"] -= 1
                    raise RuntimeError("controller transiently down")
                return await real_commit(height, commit)

            adapter.commit = flaky_commit
            net.start(init_height=1)
            await net.run_until_height(2, timeout=30)
            assert failures["seen"] >= 3  # 2 failures + ≥1 success
            await net.stop()
        run(main())


class TestWalSemantics:
    def test_no_revote_after_restart(self):
        """A restarted node must not re-vote in a round it already voted in
        (equivocation).  The WAL is written before the vote is sent."""
        async def main():
            from consensus_overlord_tpu.engine.smr import Engine

            sent = []

            class StubAdapter:
                async def get_block(self, height):
                    raise RuntimeError("not leader")

                async def check_block(self, height, block_hash, content):
                    return True

                async def commit(self, height, commit):
                    return None

                async def get_authority_list(self, height):
                    return []

                async def broadcast_to_other(self, msg_type, payload):
                    sent.append((msg_type, payload))

                async def transmit_to_relayer(self, relayer, msg_type, payload):
                    sent.append((msg_type, payload))

                def report_error(self, context):
                    pass

                def report_view_change(self, height, round, reason):
                    pass

            cryptos = [sim_crypto(bytes([i]) * 32) for i in range(1, 5)]
            from consensus_overlord_tpu.core.types import validators_to_nodes
            authority = validators_to_nodes([c.pub_key for c in cryptos])
            # Pick a node that is NOT the leader of (height=5, round=0), so
            # its prevote goes through transmit_to_relayer and is observable.
            probe = Engine(cryptos[0].pub_key, StubAdapter(), cryptos[0],
                           MemoryWal())
            probe._set_authorities(authority)
            leader = probe.leader(5, 0)
            me = next(c for c in cryptos if c.pub_key != leader)
            wal = MemoryWal()

            # First life: run briefly; propose timeout at 20ms interval makes
            # the node prevote nil quickly, writing the WAL first.
            eng = Engine(me.pub_key, StubAdapter(), me, wal)
            task = asyncio.get_running_loop().create_task(
                eng.run(5, 20, authority))
            for _ in range(100):
                await asyncio.sleep(0.01)
                if eng._my_prevote_round is not None:
                    break
            assert eng._my_prevote_round == 0
            votes_before = len(sent)
            assert votes_before >= 1
            eng.stop()
            await task

            # Second life, same WAL, same height: must restore the
            # already-voted marker and not send another prevote for round 0.
            eng2 = Engine(me.pub_key, StubAdapter(), me, wal)
            task2 = asyncio.get_running_loop().create_task(
                eng2.run(5, 20, authority))
            await asyncio.sleep(0.15)
            assert eng2._my_prevote_round == 0  # restored from WAL
            prevotes_r0 = [p for (t, p) in sent[votes_before:]
                           if t == "SignedVote"]
            from consensus_overlord_tpu.core.types import SignedVote as SV
            assert not any(SV.decode(p).vote.round == 0
                           and SV.decode(p).vote.vote_type == VoteType.PREVOTE
                           for p in prevotes_r0), "equivocated after restart"
            eng2.stop()
            await task2
        run(main())

    def test_stale_wal_lock_not_applied(self):
        """Recovery rejected as stale (controller moved on) must not leak the
        old lock into the new height."""
        async def main():
            net = SimNetwork(n_validators=4, block_interval_ms=50)
            node = net.nodes[0]
            # Hand-craft a WAL at height 2 with votes cast.
            eng = node.engine
            eng.height, eng.round = 2, 1
            eng._my_prevote_round = 1
            await eng._save_wal()
            # Start ONLY the recovered node: alone it has no quorum, so it
            # deterministically sits at the init height.
            node.start(5, net.controller.block_interval_ms,
                       net.controller.authority_list())
            await asyncio.sleep(0.05)
            assert eng.height == 5
            assert eng.lock_round is None and eng.lock_proposal is None
            # The height-2 vote marker (round 1) must not leak into height 5
            # (a fresh round-0 prevote at height 5 is fine).
            assert eng._my_prevote_round != 1
            await node.stop()
        run(main())


class TestBlsEndToEnd:
    def test_four_validators_bls(self):
        """The reference-faithful configuration: BLS12-381 aggregated
        signatures end-to-end (slow pure-Python pairing ⇒ one block)."""
        async def main():
            net = SimNetwork(
                n_validators=4, block_interval_ms=2000,
                crypto_factory=lambda i: CpuBlsCrypto(0x1000 + 7919 * i))
            net.start(init_height=1)
            await net.run_until_height(1, timeout=120)
            await net.stop()
            proof = Proof.decode(net.controller.proofs[1])
            authority = net.controller.authority_list()
            voters = extract_voters(authority, proof.signature.address_bitmap)
            vote = Vote(proof.height, proof.round, VoteType.PRECOMMIT,
                        proof.block_hash)
            assert net.nodes[0].crypto.verify_aggregated_signature(
                proof.signature.signature, sm3_hash(vote.encode()), voters)
        run(main(), timeout=180)


class TestAuthorityRefreshOnRecovery:
    def test_wal_ahead_of_init_refreshes_authorities(self):
        """A WAL recovered to a height past init_height refreshes the
        authority set through the chain port (the reference engine's
        get_authority_list callback, src/consensus.rs:659-666) — the
        caller's list describes init_height and may predate a
        reconfiguration."""
        async def main():
            from consensus_overlord_tpu.core.types import validators_to_nodes
            from consensus_overlord_tpu.engine.smr import Engine

            cryptos = [sim_crypto(bytes([i]) * 32) for i in range(1, 6)]
            old = validators_to_nodes([c.pub_key for c in cryptos[:4]])
            new = validators_to_nodes([c.pub_key for c in cryptos[1:]])
            asked = []

            class StubAdapter:
                async def get_block(self, height):
                    raise RuntimeError("no proposal")

                async def check_block(self, height, block_hash, content):
                    return True

                async def commit(self, height, commit):
                    return None

                async def get_authority_list(self, height):
                    asked.append(height)
                    return new

                async def broadcast_to_other(self, msg_type, payload):
                    pass

                async def transmit_to_relayer(self, relayer, msg_type,
                                              payload):
                    pass

                def report_error(self, context):
                    pass

                def report_view_change(self, height, round, reason):
                    pass

            # First life at height 7 writes a WAL.
            wal = MemoryWal()
            eng = Engine(cryptos[0].pub_key, StubAdapter(), cryptos[0], wal)
            task = asyncio.get_running_loop().create_task(
                eng.run(7, 20, old))
            await asyncio.sleep(0.05)
            eng.stop()
            await task

            # Second life starts at init 5 with the OLD list; WAL says 7.
            eng2 = Engine(cryptos[0].pub_key, StubAdapter(), cryptos[0],
                          wal)
            task2 = asyncio.get_running_loop().create_task(
                eng2.run(5, 20, old))
            await asyncio.sleep(0.05)
            assert asked and asked[0] == 7
            assert eng2.authorities == sorted(
                new, key=lambda n: n.address)
            eng2.stop()
            await task2

        run(main())
