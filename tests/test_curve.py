"""Device curve ops (ops/curve.py, ops/bls12381_groups.py) vs the host
BLS12-381 oracle (crypto/bls12381.py)."""

import random

import jax.numpy as jnp
import numpy as np

from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.ops.bls12381_groups import (
    FQ, FQ2, G1, G2, ParsedG1, g1_decompress_device, g1_from_oracle,
    g1_generator, g1_in_subgroup, g1_to_oracle, g2_decompress_device,
    g2_from_oracle, g2_generator, g2_in_subgroup, g2_to_oracle,
    parse_g1_compressed, parse_g2_compressed)
from consensus_overlord_tpu.ops.curve import int_to_bits_msb

RNG = random.Random(0xC17)


def rand_g1(k):
    return [oracle.g1_mul(oracle.G1_GEN, RNG.randrange(oracle.R))
            for _ in range(k)]


def rand_g2(k):
    return [oracle.g2_mul(oracle.G2_GEN, RNG.randrange(oracle.R))
            for _ in range(k)]


class TestG1Add:
    def test_add_random_and_edges(self):
        pts_a = rand_g1(4) + [None, None, oracle.G1_GEN]
        pts_b = rand_g1(4) + [oracle.G1_GEN, None, oracle.G1_GEN]
        # also P + (−P)
        p = rand_g1(1)[0]
        pts_a.append(p)
        pts_b.append(oracle.g1_neg(p))
        got = g1_to_oracle(G1.add(g1_from_oracle(pts_a), g1_from_oracle(pts_b)))
        want = [oracle.g1_add(a, b) for a, b in zip(pts_a, pts_b)]
        assert got == want

    def test_dbl(self):
        pts = rand_g1(3) + [None]
        got = g1_to_oracle(G1.dbl(g1_from_oracle(pts)))
        assert got == [oracle.g1_add(p, p) for p in pts]

    def test_on_curve_and_eq(self):
        pts = rand_g1(3) + [None]
        dev = g1_from_oracle(pts)
        assert bool(G1.on_curve(dev).all())
        bad = g1_from_oracle([(1, 1)])  # not on curve
        assert not bool(G1.on_curve(bad).any())
        assert bool(G1.eq(dev, g1_from_oracle(pts)).all())
        neq = np.asarray(G1.eq(dev, G1.dbl(dev)))
        assert list(neq) == [False, False, False, True]  # 2·∞ == ∞


class TestG1ScalarMul:
    def test_scalar_mul_bits(self):
        ks = [0, 1, 2, oracle.R - 1] + [RNG.randrange(oracle.R)
                                        for _ in range(4)]
        bits = int_to_bits_msb(ks, 256)
        got = g1_to_oracle(G1.scalar_mul_bits(g1_generator(len(ks)), bits))
        assert got == [oracle.g1_mul(oracle.G1_GEN, k) for k in ks]

    def test_scalar_mul_static_order(self):
        pts = rand_g1(2)
        res = G1.scalar_mul_static(g1_from_oracle(pts), oracle.R)
        assert bool(G1.is_infinity(res).all())

    def test_tree_sum(self):
        pts = rand_g1(5)  # odd count exercises padding
        (got,) = g1_to_oracle(G1.tree_sum(g1_from_oracle(pts)))
        want = None
        for p in pts:
            want = oracle.g1_add(want, p)
        assert got == want


class TestG1Decompress:
    def test_roundtrip_and_badpoints(self):
        pts = rand_g1(4) + [None]
        blobs = [oracle.g1_compress(p) for p in pts]
        blobs += [b"\x00" * 48,               # compressed flag missing
                  bytes([0xC0 | 0x20]) + b"\x00" * 47,  # bad infinity
                  b"short"]
        # an x not on the curve: find one deterministically
        x = 5
        while oracle.fq_sqrt((x**3 + 4) % oracle.P) is not None:
            x += 1
        blobs.append(bytes([0x80 | (x >> 376)]) + (x % (1 << 376)).to_bytes(47, "big"))
        parsed = parse_g1_compressed(blobs)
        pt, valid = g1_decompress_device(
            jnp.asarray(parsed.x), jnp.asarray(parsed.sign),
            jnp.asarray(parsed.infinity), jnp.asarray(parsed.wellformed))
        valid = np.asarray(valid)
        assert list(valid) == [True] * 5 + [False] * 4
        got = g1_to_oracle(pt)
        assert got[:5] == pts


class TestG1Subgroup:
    def test_subgroup_detects_cofactor_points(self):
        # A curve point NOT in the r-subgroup: hash an x until on-curve,
        # skip the cofactor clearing.
        x = 2
        while True:
            y = oracle.fq_sqrt((x**3 + 4) % oracle.P)
            if y is not None and not oracle.g1_in_subgroup((x, y)):
                break
            x += 1
        good = rand_g1(2)
        batch = g1_from_oracle(good + [(x, y), None])
        got = list(np.asarray(g1_in_subgroup(batch)))
        assert got == [True, True, False, True]


class TestG2:
    def test_add_mul_vs_oracle(self):
        pts = rand_g2(2) + [None]
        ks = [3, RNG.randrange(oracle.R), 7]
        dev = g2_from_oracle(pts)
        got = g2_to_oracle(G2.add(dev, dev))
        assert got == [oracle.g2_add(p, p) for p in pts]
        bits = int_to_bits_msb(ks, 256)
        got = g2_to_oracle(G2.scalar_mul_bits(dev, bits))
        assert got == [oracle.g2_mul(p, k) for p, k in zip(pts, ks)]

    def test_on_curve(self):
        dev = g2_from_oracle(rand_g2(2) + [None])
        assert bool(G2.on_curve(dev).all())

    def test_decompress_roundtrip(self):
        pts = rand_g2(3) + [None]
        blobs = [oracle.g2_compress(p) for p in pts] + [b"\x00" * 96]
        parsed = parse_g2_compressed(blobs)
        pt, valid = g2_decompress_device(
            jnp.asarray(parsed.x), jnp.asarray(parsed.sign),
            jnp.asarray(parsed.infinity), jnp.asarray(parsed.wellformed))
        assert list(np.asarray(valid)) == [True] * 4 + [False]
        assert g2_to_oracle(pt)[:4] == pts

    def test_subgroup(self):
        dev = g2_from_oracle(rand_g2(2) + [None])
        assert list(np.asarray(g2_in_subgroup(dev))) == [True, True, True]

    def test_tree_sum(self):
        pts = rand_g2(3)
        (got,) = g2_to_oracle(G2.tree_sum(g2_from_oracle(pts)))
        want = None
        for p in pts:
            want = oracle.g2_add(want, p)
        assert got == want


class TestEndomorphismSubgroupChecks:
    """The fast φ/ψ membership criteria must agree with the naive
    [r]P == 𝒪 semantics on members, cofactor points, and infinity."""

    def _g1_cofactor_point(self):
        x = 2
        while True:
            y = oracle.fq_sqrt((x**3 + 4) % oracle.P)
            if y is not None and not oracle.g1_in_subgroup((x, y)):
                return (x, y)
            x += 1

    def _g2_cofactor_point(self):
        i = 1
        while True:
            x = (i, i + 1)
            rhs = oracle.fq2_add(
                oracle.fq2_mul(oracle.fq2_sq(x), x), (4, 4))
            y = oracle.fq2_sqrt(rhs)
            if y is not None and not oracle.g2_in_subgroup((x, y)):
                return (x, y)
            i += 1

    def test_g1_fast_vs_full(self):
        from consensus_overlord_tpu.ops.bls12381_groups import (
            g1_in_subgroup_full)
        batch = g1_from_oracle(rand_g1(2) + [self._g1_cofactor_point(), None])
        fast = list(np.asarray(g1_in_subgroup(batch)))
        full = list(np.asarray(g1_in_subgroup_full(batch)))
        assert fast == full == [True, True, False, True]

    def test_g2_fast_vs_full(self):
        from consensus_overlord_tpu.ops.bls12381_groups import (
            g2_in_subgroup_full)
        batch = g2_from_oracle(rand_g2(2) + [self._g2_cofactor_point(), None])
        fast = list(np.asarray(g2_in_subgroup(batch)))
        full = list(np.asarray(g2_in_subgroup_full(batch)))
        assert fast == full == [True, True, False, True]

    def test_endomorphism_constants_vs_oracle(self):
        """β acts as λ = −z² on G1; ψ acts as z on G2 (host-side check of
        the embedded constants against the oracle)."""
        from consensus_overlord_tpu.ops.bls12381_groups import (
            _BETA_INT, _PSI_CX_INT, _PSI_CY_INT, Z_ABS)
        z = -Z_ABS
        assert (z**4 - z**2 + 1) == oracle.R
        lam = (-z * z) % oracle.R
        assert (lam * lam + lam + 1) % oracle.R == 0
        g = oracle.G1_GEN
        assert ((g[0] * _BETA_INT) % oracle.P, g[1]) == oracle.g1_mul(g, lam)
        q = oracle.G2_GEN
        psi_q = (oracle.fq2_mul(oracle.fq2_conj(q[0]), _PSI_CX_INT),
                 oracle.fq2_mul(oracle.fq2_conj(q[1]), _PSI_CY_INT))
        assert psi_q == oracle.g2_mul(q, z % oracle.R)


class TestMsmBits:
    """msm_bits (the MSM under the RLC batch verification) must agree
    bit-for-bit with tree_sum(scalar_mul_bits(...)) and the oracle's
    linear combination for every scalar shape the provider generates
    (64-bit weights, zero-weight padding lanes, infinity lanes)."""

    def _scalars(self):
        ks = [RNG.randrange(2**64) for _ in range(8)]
        ks[2] = 0                 # padding lane weight
        ks[5] = 2**64 - 1         # max recode carry chain
        ks[6] = 1
        return ks

    def test_g1_msm_vs_oracle(self):
        pts = rand_g1(8)
        ks = self._scalars()
        bits = int_to_bits_msb(ks, 64)
        dev_pts = g1_from_oracle(pts)
        (got,) = g1_to_oracle(G1.msm_bits(dev_pts, bits))
        (old,) = g1_to_oracle(
            G1.tree_sum(G1.scalar_mul_bits(dev_pts, bits)))
        want = None
        for p, k in zip(pts, ks):
            want = oracle.g1_add(want, oracle.g1_mul(p, k))
        assert got == old == want

    def test_g2_msm_vs_oracle(self):
        pts = rand_g2(8)
        ks = self._scalars()
        bits = int_to_bits_msb(ks, 64)
        (got,) = g2_to_oracle(G2.msm_bits(g2_from_oracle(pts), bits))
        want = None
        for p, k in zip(pts, ks):
            want = oracle.g2_add(want, oracle.g2_mul(p, k))
        assert got == want

    def test_infinity_lanes_and_all_zero(self):
        pts = [None, None] + rand_g1(2)
        ks = self._scalars()[:4]
        bits = int_to_bits_msb(ks, 64)
        (got,) = g1_to_oracle(G1.msm_bits(g1_from_oracle(pts), bits))
        want = None
        for p, k in zip(pts, ks):
            want = oracle.g1_add(want, oracle.g1_mul(p, k) if p else None)
        assert got == want
        zero = int_to_bits_msb([0, 0, 0, 0], 64)
        (z,) = g1_to_oracle(G1.msm_bits(g1_from_oracle(pts), zero))
        assert z is None
