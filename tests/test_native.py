"""Cross-validation of the native C BLS12-381 backend (csrc/bls381.c)
against the pure-Python oracle — layer by layer (fp12 mul/inv, Miller
loop, final exponentiation, full pairing) and at the dispatch surface
(multi_pairing_is_one must agree with multi_pairing_is_one_pure)."""

import ctypes
import random
import unittest

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.crypto import native


def _rand_fq12(rng):
    return tuple(
        tuple((rng.randrange(oracle.P), rng.randrange(oracle.P))
              for _ in range(3))
        for _ in range(2))


def _pack_fp12(f12):
    out = []
    for c6 in f12:
        for c2 in c6:
            for c in c2:
                out.extend(native._fp_limbs(c))
    return (ctypes.c_uint64 * 72)(*out)


@unittest.skipUnless(native.available(), "no C compiler for native backend")
class TestNativeBackend(unittest.TestCase):
    def setUp(self):
        self.rng = random.Random(0xB15381)

    def test_fp_mul_and_inv(self):
        lib = native._load()
        for _ in range(20):
            a = self.rng.randrange(oracle.P)
            b = self.rng.randrange(1, oracle.P)
            av = (ctypes.c_uint64 * 6)(*native._fp_limbs(a))
            bv = (ctypes.c_uint64 * 6)(*native._fp_limbs(b))
            out = (ctypes.c_uint64 * 6)()
            lib.bls381_fp_mul(av, bv, out)
            self.assertEqual(native._limbs_to_int(list(out)),
                             a * b % oracle.P)
            lib.bls381_fp_inv(bv, out)
            self.assertEqual(native._limbs_to_int(list(out)),
                             oracle.fq_inv(b))

    def test_fp12_mul_inv(self):
        lib = native._load()
        for _ in range(5):
            a = _rand_fq12(self.rng)
            b = _rand_fq12(self.rng)
            out = (ctypes.c_uint64 * 72)()
            lib.bls381_fp12_mul(_pack_fp12(a), _pack_fp12(b), out)
            self.assertEqual(native._fp12_out_to_tuple(list(out)),
                             oracle.fq12_mul(a, b))
            lib.bls381_fp12_inv(_pack_fp12(a), out)
            self.assertEqual(native._fp12_out_to_tuple(list(out)),
                             oracle.fq12_inv(a))

    def test_final_exp_matches_oracle(self):
        lib = native._load()
        f = _rand_fq12(self.rng)
        out = (ctypes.c_uint64 * 72)()
        lib.bls381_final_exp(_pack_fp12(f), out)
        self.assertEqual(native._fp12_out_to_tuple(list(out)),
                         oracle.final_exponentiation(f))

    def test_pairing_matches_oracle(self):
        """Full pairings must agree exactly.  (Raw Miller values differ by
        design: the native projective line coefficients carry Fp2/Fp6
        subfield scale factors the final exponentiation annihilates.)"""
        for k1, k2 in ((1, 1), (7, 11), (123456789, 987654321)):
            p = oracle.g1_mul(oracle.G1_GEN, k1)
            q = oracle.g2_mul(oracle.G2_GEN, k2)
            self.assertEqual(native.pairing(p, q), oracle.pairing(q, p))

    def test_pairing_bilinearity(self):
        p = oracle.g1_mul(oracle.G1_GEN, 5)
        q = oracle.g2_mul(oracle.G2_GEN, 9)
        self.assertEqual(native.pairing(oracle.g1_mul(p, 3), q),
                         native.pairing(p, oracle.g2_mul(q, 3)))

    def test_multi_pairing_dispatch_agrees_with_pure(self):
        h = sm3_hash(b"native-vs-pure")
        sk = 0xC0FFEE
        sig = oracle.g1_decompress(oracle.sign(sk, h))
        pk = oracle.g2_decompress(oracle.sk_to_pk(sk))
        hp = oracle.hash_to_g1(h, b"")
        neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
        good = [(sig, neg_g2), (hp, pk)]
        self.assertTrue(native.multi_pairing_is_one(good))
        self.assertTrue(oracle.multi_pairing_is_one_pure(good))
        bad_h = oracle.hash_to_g1(sm3_hash(b"other"), b"")
        bad = [(sig, neg_g2), (bad_h, pk)]
        self.assertFalse(native.multi_pairing_is_one(bad))
        self.assertFalse(oracle.multi_pairing_is_one_pure(bad))
        # infinity lanes are skipped on both paths
        self.assertTrue(native.multi_pairing_is_one(
            [(None, neg_g2), (hp, None)]))
        self.assertTrue(oracle.multi_pairing_is_one_pure(
            [(None, neg_g2), (hp, None)]))

    def test_verify_through_dispatcher(self):
        """oracle.verify now routes pairings through the native backend;
        sign/verify round-trips and rejects must behave identically."""
        h = sm3_hash(b"dispatcher")
        sig = oracle.sign(42, h)
        pk = oracle.sk_to_pk(42)
        self.assertTrue(oracle.verify(pk, h, sig))
        self.assertFalse(oracle.verify(pk, sm3_hash(b"not it"), sig))
        self.assertFalse(oracle.verify(oracle.sk_to_pk(43), h, sig))


if __name__ == "__main__":
    unittest.main()
