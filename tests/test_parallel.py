"""Sharded crypto step over the virtual 8-device CPU mesh vs the oracle."""

import random

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.ops import bls12381_groups as dev
from consensus_overlord_tpu.ops.curve import int_to_bits_msb
from consensus_overlord_tpu.parallel import (
    make_mesh, sharded_round_step, sharded_verify_round)

RNG = random.Random(0x5A)
B = 16
NBITS = 32  # short scalars keep the test compile cheap; shape-generic code


@pytest.fixture(scope="module")
def fixture_data():
    sks = [RNG.randrange(2, oracle.R) for _ in range(B)]
    msg = b"round-msg"
    sigs = [oracle.sign(sk, msg) for sk in sks]
    pks = [oracle.sk_to_pk(sk) for sk in sks]
    scalars = [RNG.randrange(1, 1 << NBITS) for _ in range(B)]
    return msg, sigs, pks, scalars


def test_sharded_verify_round_matches_oracle(fixture_data):
    """The production fused kernel over the 8-device mesh: 2 lanes per
    device, pubkey cache replicated + gathered by sharded row index —
    both MSM aggregates must equal the oracle's linear combinations."""
    msg, sigs, pks, scalars = fixture_data
    assert len(jax.devices()) >= 8
    mesh = make_mesh(8)
    fn = sharded_verify_round(mesh)
    parsed = dev.parse_g1_compressed(sigs)
    wpacked = jnp.asarray(np.frombuffer(
        b"".join(r.to_bytes(8, "big") for r in scalars),
        np.uint8).reshape(B, 8))
    pks_aff = [oracle.g2_decompress(p) for p in pks]
    pk_pt = dev.g2_from_oracle(pks_aff)
    rows = jnp.asarray(np.arange(B, dtype=np.int64))
    ax, ay, ainf, valid, gx, gy, ginf = fn(
        jnp.asarray(parsed.x), jnp.asarray(parsed.sign),
        jnp.asarray(parsed.infinity), jnp.asarray(parsed.wellformed),
        wpacked, rows, pk_pt.x, pk_pt.y, pk_pt.z)
    assert list(np.asarray(valid)) == [True] * B
    want = None
    for s, r in zip(sigs, scalars):
        want = oracle.g1_add(want, oracle.g1_mul(oracle.g1_decompress(s), r))
    got = (dev.FQ.ints_from_strict(np.asarray(ax))[0],
           dev.FQ.ints_from_strict(np.asarray(ay))[0])
    assert got == want
    want2 = None
    for p, r in zip(pks_aff, scalars):
        want2 = oracle.g2_add(want2, oracle.g2_mul(p, r))
    got2 = (tuple(dev.FQ.ints_from_strict(np.asarray(gx))),
            tuple(dev.FQ.ints_from_strict(np.asarray(gy))))
    assert got2 == want2


def test_sharded_round_step_runs_and_aggregates(fixture_data):
    msg, sigs, pks, scalars = fixture_data
    mesh = make_mesh(8)
    step = sharded_round_step(mesh)
    parsed = dev.parse_g1_compressed(sigs)
    pk_parsed = dev.parse_g2_compressed(pks)
    pk_pt, pk_ok = dev.g2_decompress_device(
        jnp.asarray(pk_parsed.x), jnp.asarray(pk_parsed.sign),
        jnp.asarray(pk_parsed.infinity), jnp.asarray(pk_parsed.wellformed))
    assert bool(np.asarray(pk_ok).all())
    bits = int_to_bits_msb(scalars, NBITS)
    out = step(jnp.asarray(parsed.x), jnp.asarray(parsed.sign),
               jnp.asarray(parsed.infinity), jnp.asarray(parsed.wellformed),
               pk_pt.x, pk_pt.y, pk_pt.z, bits)
    (ax1, ay1, ai1, ax2, ay2, ai2, ax3, ay3, ai3, valid) = out
    assert list(np.asarray(valid)) == [True] * B
    # QC aggregate (unit weights) must equal the oracle signature sum.
    want = None
    for s in sigs:
        want = oracle.g1_add(want, oracle.g1_decompress(s))
    assert (dev.FQ.to_ints(ax3)[0], dev.FQ.to_ints(ay3)[0]) == want
    # G2 RLC must equal Σ r_i·P_i.
    want2 = None
    for p, r in zip(pks, scalars):
        want2 = oracle.g2_add(want2, oracle.g2_mul(oracle.g2_decompress(p), r))
    (x_pair,) = dev.FQ2.to_int_pairs(ax2)
    (y_pair,) = dev.FQ2.to_int_pairs(ay2)
    assert (x_pair, y_pair) == want2


def test_provider_over_mesh_end_to_end():
    """TpuBlsCrypto(mesh=...) — the production provider API — verifies,
    aggregates, and audits over the virtual 8-device mesh (the capability
    the driver dryrun certifies, __graft_entry__.dryrun_multichip)."""
    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
    from consensus_overlord_tpu.parallel import make_mesh

    mesh = make_mesh(8)
    provider = TpuBlsCrypto(0xD1CE, device_threshold=1, mesh=mesh)
    batch = 16
    h = sm3_hash(b"mesh-provider-block")
    sks = [7000 + 13 * i for i in range(batch)]
    sigs = [oracle.sign(sk, h) for sk in sks]
    pks = [oracle.sk_to_pk(sk) for sk in sks]

    provider.update_pubkeys(pks)
    assert provider.verify_batch(sigs, [h] * batch, pks) == [True] * batch

    # one corrupted lane: the batch relation fails and per-lane fallback
    # localizes exactly the bad signature
    bad = list(sigs)
    bad[3] = oracle.sign(sks[3], sm3_hash(b"other message"))
    got = provider.verify_batch(bad, [h] * batch, pks)
    assert got == [i != 3 for i in range(batch)]

    agg = provider.aggregate_signatures(sigs, pks)
    want = None
    for s in sigs:
        want = oracle.g1_add(want, oracle.g1_decompress(s))
    assert agg == oracle.g1_compress(want)
    assert provider.verify_aggregated_signature(agg, h, pks)
    assert not provider.verify_aggregated_signature(agg, sm3_hash(b"x"), pks)
