"""Sharded crypto step over the virtual 8-device CPU mesh vs the oracle."""

import random

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.ops import bls12381_groups as dev
from consensus_overlord_tpu.ops.curve import int_to_bits_msb
from consensus_overlord_tpu.parallel import (
    make_mesh, sharded_round_step, sharded_verify_round)

RNG = random.Random(0x5A)
B = 16
NBITS = 32  # short scalars keep the test compile cheap; shape-generic code


@pytest.fixture(scope="module")
def fixture_data():
    sks = [RNG.randrange(2, oracle.R) for _ in range(B)]
    msg = b"round-msg"
    sigs = [oracle.sign(sk, msg) for sk in sks]
    pks = [oracle.sk_to_pk(sk) for sk in sks]
    scalars = [RNG.randrange(1, 1 << NBITS) for _ in range(B)]
    return msg, sigs, pks, scalars


def test_sharded_verify_round_matches_oracle(fixture_data):
    """The production fused kernel over the 8-device mesh: 2 lanes per
    device, pubkey cache replicated + gathered by sharded row index —
    both MSM aggregates must equal the oracle's linear combinations."""
    msg, sigs, pks, scalars = fixture_data
    assert len(jax.devices()) >= 8
    mesh = make_mesh(8)
    fn = sharded_verify_round(mesh)
    parsed = dev.parse_g1_compressed(sigs)
    wpacked = jnp.asarray(np.frombuffer(
        b"".join(r.to_bytes(8, "big") for r in scalars),
        np.uint8).reshape(B, 8))
    pks_aff = [oracle.g2_decompress(p) for p in pks]
    pk_pt = dev.g2_from_oracle(pks_aff)
    rows = jnp.asarray(np.arange(B, dtype=np.int64))
    ax, ay, ainf, valid, gx, gy, ginf = fn(
        jnp.asarray(parsed.x), jnp.asarray(parsed.sign),
        jnp.asarray(parsed.infinity), jnp.asarray(parsed.wellformed),
        wpacked, rows, pk_pt.x, pk_pt.y, pk_pt.z)
    assert list(np.asarray(valid)) == [True] * B
    want = None
    for s, r in zip(sigs, scalars):
        want = oracle.g1_add(want, oracle.g1_mul(oracle.g1_decompress(s), r))
    got = (dev.FQ.ints_from_strict(np.asarray(ax))[0],
           dev.FQ.ints_from_strict(np.asarray(ay))[0])
    assert got == want
    want2 = None
    for p, r in zip(pks_aff, scalars):
        want2 = oracle.g2_add(want2, oracle.g2_mul(p, r))
    got2 = (tuple(dev.FQ.ints_from_strict(np.asarray(gx))),
            tuple(dev.FQ.ints_from_strict(np.asarray(gy))))
    assert got2 == want2


def test_sharded_round_step_runs_and_aggregates(fixture_data):
    msg, sigs, pks, scalars = fixture_data
    mesh = make_mesh(8)
    step = sharded_round_step(mesh)
    parsed = dev.parse_g1_compressed(sigs)
    pk_parsed = dev.parse_g2_compressed(pks)
    pk_pt, pk_ok = dev.g2_decompress_device(
        jnp.asarray(pk_parsed.x), jnp.asarray(pk_parsed.sign),
        jnp.asarray(pk_parsed.infinity), jnp.asarray(pk_parsed.wellformed))
    assert bool(np.asarray(pk_ok).all())
    bits = int_to_bits_msb(scalars, NBITS)
    out = step(jnp.asarray(parsed.x), jnp.asarray(parsed.sign),
               jnp.asarray(parsed.infinity), jnp.asarray(parsed.wellformed),
               pk_pt.x, pk_pt.y, pk_pt.z, bits)
    (ax1, ay1, ai1, ax2, ay2, ai2, ax3, ay3, ai3, valid) = out
    assert list(np.asarray(valid)) == [True] * B
    # QC aggregate (unit weights) must equal the oracle signature sum.
    want = None
    for s in sigs:
        want = oracle.g1_add(want, oracle.g1_decompress(s))
    assert (dev.FQ.to_ints(ax3)[0], dev.FQ.to_ints(ay3)[0]) == want
    # G2 RLC must equal Σ r_i·P_i.
    want2 = None
    for p, r in zip(pks, scalars):
        want2 = oracle.g2_add(want2, oracle.g2_mul(oracle.g2_decompress(p), r))
    (x_pair,) = dev.FQ2.to_int_pairs(ax2)
    (y_pair,) = dev.FQ2.to_int_pairs(ay2)
    assert (x_pair, y_pair) == want2


def test_provider_over_mesh_end_to_end():
    """TpuBlsCrypto(mesh=...) — the production provider API — verifies,
    aggregates, and audits over the virtual 8-device mesh (the capability
    the driver dryrun certifies, __graft_entry__.dryrun_multichip)."""
    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
    from consensus_overlord_tpu.parallel import make_mesh

    mesh = make_mesh(8)
    provider = TpuBlsCrypto(0xD1CE, device_threshold=1, mesh=mesh)
    batch = 16
    h = sm3_hash(b"mesh-provider-block")
    sks = [7000 + 13 * i for i in range(batch)]
    sigs = [oracle.sign(sk, h) for sk in sks]
    pks = [oracle.sk_to_pk(sk) for sk in sks]

    provider.update_pubkeys(pks)
    assert provider.verify_batch(sigs, [h] * batch, pks) == [True] * batch

    # one corrupted lane: the batch relation fails and per-lane fallback
    # localizes exactly the bad signature
    bad = list(sigs)
    bad[3] = oracle.sign(sks[3], sm3_hash(b"other message"))
    got = provider.verify_batch(bad, [h] * batch, pks)
    assert got == [i != 3 for i in range(batch)]

    agg = provider.aggregate_signatures(sigs, pks)
    want = None
    for s in sigs:
        want = oracle.g1_add(want, oracle.g1_decompress(s))
    assert agg == oracle.g1_compress(want)
    assert provider.verify_aggregated_signature(agg, h, pks)
    assert not provider.verify_aggregated_signature(agg, sm3_hash(b"x"), pks)


# ---------------------------------------------------------------------------
# Sharded pairing (r14): the mesh path's device verdict vs the host oracle
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh_pairing_provider():
    """A provider whose kernel set is the 8-device mesh WITH the sharded
    staged pairing on — the production mesh hot path under test."""
    from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto

    mesh = make_mesh(8)
    provider = TpuBlsCrypto(0xD1CE, device_threshold=1, mesh=mesh,
                            device_pairing=True)
    sks = [7000 + 13 * i for i in range(B)]
    provider.update_pubkeys([oracle.sk_to_pk(sk) for sk in sks])
    return provider, sks


class TestShardedPairingKernels:
    """parallel/sharded.py sharded_multi_pairing_is_one directly: verdict
    bit-identity vs crypto/bls12381.py multi_pairing_is_one over the
    8-device mesh, valid + invalid + padding lanes."""

    def _verdict(self, fn, pairs, size):
        """Run `fn` on `pairs` padded up to `size` with masked lanes."""
        from consensus_overlord_tpu.ops import pairing as pr

        pad = [None] * (size - len(pairs))
        px, py, pinf = pr.g1_affine_from_oracle(
            [p for p, _q in pairs] + pad)
        qx, qy, qinf = pr.g2_affine_from_oracle(
            [q for _p, q in pairs] + pad)
        mask = np.arange(size) < len(pairs)
        return bool(fn(jnp.asarray(px), jnp.asarray(py),
                       jnp.asarray(pinf), jnp.asarray(qx),
                       jnp.asarray(qy), jnp.asarray(qinf),
                       jnp.asarray(mask)))

    def test_verdict_identity_valid_invalid_padding(self):
        from consensus_overlord_tpu.core.sm3 import sm3_hash
        from consensus_overlord_tpu.parallel import (
            sharded_multi_pairing_is_one)

        mesh = make_mesh(8)
        fn = sharded_multi_pairing_is_one(mesh)
        neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
        for i in range(4):
            sk = RNG.randrange(2, oracle.R)
            h = sm3_hash(b"mesh-pairing-%d" % i)
            sig = oracle.g1_decompress(oracle.sign(sk, h))
            pk = oracle.g2_decompress(oracle.sk_to_pk(sk))
            if i % 2 == 1:
                sig = oracle.g1_mul(sig, 7)  # forged: valid point, wrong sig
            h_pt = oracle.hash_to_g1(h, b"")
            pairs = [(sig, neg_g2), (h_pt, pk)]
            got = self._verdict(fn, pairs, 8)  # 6 padding lanes
            host = oracle.multi_pairing_is_one(pairs)
            assert got is host is (i % 2 == 0)

    def test_infinity_pairs_skip_like_host(self):
        """An infinity input skips its lane on device exactly as the
        host's None pairs do — over the mesh, with padding live too."""
        from consensus_overlord_tpu.core.sm3 import sm3_hash
        from consensus_overlord_tpu.parallel import (
            sharded_multi_pairing_is_one)

        mesh = make_mesh(8)
        fn = sharded_multi_pairing_is_one(mesh)
        neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
        sk = RNG.randrange(2, oracle.R)
        h = sm3_hash(b"mesh-pairing-inf")
        sig = oracle.g1_decompress(oracle.sign(sk, h))
        pk = oracle.g2_decompress(oracle.sk_to_pk(sk))
        h_pt = oracle.hash_to_g1(h, b"")
        pairs = [(sig, neg_g2), (h_pt, pk), (None, pk), (h_pt, None)]
        got = self._verdict(fn, pairs, 8)
        host = oracle.multi_pairing_is_one(
            [(sig, neg_g2), (h_pt, pk), (None, pk), (h_pt, None)])
        assert got is host is True


class TestMeshConfigKnob:
    """service/config.py `mesh` knob → service/consensus._make_mesh →
    the provider's kernel-set selection."""

    def test_values_validate(self):
        from consensus_overlord_tpu.service.config import ConsensusConfig
        for mode in ("off", "local", "global"):
            assert ConsensusConfig(mesh=mode).mesh == mode
        with pytest.raises(ValueError):
            ConsensusConfig(mesh="ici")

    def test_make_mesh_modes(self):
        from consensus_overlord_tpu.service.consensus import _make_mesh
        assert _make_mesh("off") is None
        local = _make_mesh("local")
        assert local is not None and local.devices.size == len(jax.devices())
        # single process: "global" degenerates to the same device set
        # (init_multihost returns False without a coordinator)
        glob = _make_mesh("global")
        assert glob.devices.size == local.devices.size


class TestMeshProviderPairing:
    """The provider surface on the mesh path with device pairing on —
    the single-chip suite's contracts (tests/test_pairing.py
    TestProviderDevicePairing) must hold unchanged over the mesh."""

    def test_verify_batch_exact_no_fallbacks(self, mesh_pairing_provider):
        from consensus_overlord_tpu.core.sm3 import sm3_hash

        provider, sks = mesh_pairing_provider
        h = sm3_hash(b"mesh-dev-pairing-1")
        sigs = [oracle.sign(sk, h) for sk in sks]
        pks = [oracle.sk_to_pk(sk) for sk in sks]
        sigs[2] = oracle.sign(sks[2], sm3_hash(b"wrong"))
        got = provider.verify_batch(sigs, [h] * B, pks)
        assert got == [i != 2 for i in range(B)]
        assert provider.pairing_host_fallbacks == 0

    def test_one_final_exp_per_flush_on_mesh(self, mesh_pairing_provider):
        """pairing stage count == flush count over the mesh: the sharded
        staged pair still pays ONE shared final exponentiation per
        frontier flush, never one per signature."""
        from consensus_overlord_tpu.core.sm3 import sm3_hash
        from consensus_overlord_tpu.obs.prof import DeviceProfiler

        provider, sks = mesh_pairing_provider
        prof = DeviceProfiler()
        provider.bind_profiler(prof)
        try:
            h = sm3_hash(b"mesh-dev-pairing-flushes")
            sigs = [oracle.sign(sk, h) for sk in sks]
            pks = [oracle.sk_to_pk(sk) for sk in sks]
            flushes = 3
            for _ in range(flushes):
                assert all(provider.verify_batch(sigs, [h] * B, pks))
            totals = prof.stage_totals()
            assert totals["verify_batch/pairing"]["count"] == flushes
            assert totals["verify_batch/readback"]["count"] == flushes
        finally:
            provider.bind_profiler(None)
        assert provider.pairing_host_fallbacks == 0

    def test_multi_hash_fused_on_mesh(self, mesh_pairing_provider):
        from consensus_overlord_tpu.core.sm3 import sm3_hash

        provider, sks = mesh_pairing_provider
        h1, h2 = sm3_hash(b"mesh-mh-a"), sm3_hash(b"mesh-mh-b")
        hashes = [h1 if i % 2 == 0 else h2 for i in range(B)]
        sigs = [oracle.sign(sks[i], hashes[i]) for i in range(B)]
        pks = [oracle.sk_to_pk(sk) for sk in sks]
        assert provider.verify_batch(sigs, hashes, pks) == [True] * B
        assert provider.pairing_host_fallbacks == 0

    def test_injected_fault_breaker_host_fallback(self, monkeypatch):
        """A device fault on the MESH pairing dispatch degrades exactly
        like the single-chip path: breaker fed, fallback counted, host
        oracle verdicts exact."""
        from consensus_overlord_tpu.core.sm3 import sm3_hash
        from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto

        mesh = make_mesh(8)
        t = TpuBlsCrypto(0xD1CE, device_threshold=1, mesh=mesh,
                         device_pairing=True)
        sks = [7000 + 13 * i for i in range(B)]
        pks = [oracle.sk_to_pk(sk) for sk in sks]
        t.update_pubkeys(pks)

        def boom(*_a):
            raise RuntimeError("injected mesh pairing fault")

        monkeypatch.setattr(t._kernels, "multi_pairing", boom)
        h = sm3_hash(b"mesh-fault-pairing")
        sigs = [oracle.sign(sk, h) for sk in sks]
        sigs[4] = oracle.sign(sks[4], sm3_hash(b"nope"))
        got = t.verify_batch(sigs, [h] * B, pks)
        assert got == [i != 4 for i in range(B)]
        assert t.pairing_host_fallbacks >= 1
        assert t.breaker.status()["state"] != "open"  # one fault ≠ open
        assert t.degraded_status()["pairing_host_fallbacks"] >= 1
