"""Device Ed25519 batch verification vs the host `cryptography` backend
(RFC 8032 signatures): curve ops, decompression, and the end-to-end
batch relation with exact per-lane localization.

The batch-relation tests compare against the host backend, so they
require the optional `cryptography` package (the curve-op tests below
don't, and still run without it)."""

import unittest

import jax.numpy as jnp
import numpy as np
import pytest

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto.ed25519_tpu import Ed25519TpuCrypto
from consensus_overlord_tpu.crypto.provider import Ed25519Crypto
from consensus_overlord_tpu.ops import edwards as ed


class TestEdwardsOps(unittest.TestCase):
    def test_decompress_base_point(self):
        enc = (ed._B_Y | ((ed._B_X & 1) << 255)).to_bytes(32, "little")
        parsed = ed.parse_points([enc])
        pt, valid = ed.decompress(jnp.asarray(parsed.y),
                                  jnp.asarray(parsed.sign))
        self.assertTrue(bool(valid[0]))
        (x,) = ed.FE.to_ints(pt.x)
        (y,) = ed.FE.to_ints(pt.y)
        self.assertEqual(x, ed._B_X)
        self.assertEqual(y, ed._B_Y)

    def test_bad_point_rejected(self):
        # y = 2 is not on the curve (x^2 would be non-square)
        bad = (2).to_bytes(32, "little")
        parsed = ed.parse_points([bad])
        _, valid = ed.decompress(jnp.asarray(parsed.y),
                                 jnp.asarray(parsed.sign))
        self.assertFalse(bool(valid[0]))
        # non-canonical y >= p rejected at parse
        noncanon = (ed.P + 1).to_bytes(32, "little")
        parsed = ed.parse_points([noncanon])
        self.assertFalse(bool(parsed.wellformed[0]))

    def test_scalar_mul_matches_host(self):
        """[k]B on device == host reference (affine double-and-add in
        Python ints)."""
        def host_add(p, q):
            (x1, y1), (x2, y2) = p, q
            x1y2, x2y1 = x1 * y2 % ed.P, x2 * y1 % ed.P
            y1y2, x1x2 = y1 * y2 % ed.P, x1 * x2 % ed.P
            dxy = ed.D * x1x2 % ed.P * y1y2 % ed.P
            x3 = (x1y2 + x2y1) * pow(1 + dxy, ed.P - 2, ed.P) % ed.P
            y3 = (y1y2 + x1x2) * pow(1 - dxy + ed.P, ed.P - 2, ed.P) % ed.P
            return (x3, y3)

        for k in (1, 2, 3, 7, 0xDEAD):
            want = (0, 1)
            for bit in bin(k)[2:]:
                want = host_add(want, want)
                if bit == "1":
                    want = host_add(want, (ed._B_X, ed._B_Y))
            bits = jnp.asarray(ed.int_to_bits_msb([k], 16))
            pt = ed.scalar_mul_bits(ed.base_point(1), bits)
            zi = pow(int(ed.FE.to_ints(pt.z)[0]), ed.P - 2, ed.P)
            x = int(ed.FE.to_ints(pt.x)[0]) * zi % ed.P
            y = int(ed.FE.to_ints(pt.y)[0]) * zi % ed.P
            self.assertEqual((x, y), want, k)


class TestEd25519Batch(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        # The host twin these tests compare against IS Ed25519Crypto —
        # the sim fallback would be circular; skip without the backend.
        pytest.importorskip("cryptography")
        cls.cryptos = [Ed25519Crypto(bytes([i]) * 32) for i in range(1, 9)]
        cls.msgs = [sm3_hash(b"ed-batch-%d" % i) for i in range(8)]
        cls.sigs = [c.sign(m) for c, m in zip(cls.cryptos, cls.msgs)]
        cls.pks = [c.pub_key for c in cls.cryptos]
        cls.prov = Ed25519TpuCrypto(b"\x99" * 32, device_threshold=1)

    def test_all_valid(self):
        got = self.prov.verify_batch(self.sigs, self.msgs, self.pks)
        self.assertEqual(got, [True] * 8)

    def test_bad_lane_localized(self):
        sigs = list(self.sigs)
        bad = bytearray(sigs[5])
        bad[2] ^= 0xFF
        sigs[5] = bytes(bad)
        got = self.prov.verify_batch(sigs, self.msgs, self.pks)
        self.assertEqual(got, [True] * 5 + [False] + [True] * 2)

    def test_wrong_signer_localized(self):
        sigs = list(self.sigs)
        sigs[0] = self.cryptos[1].sign(self.msgs[0])
        got = self.prov.verify_batch(sigs, self.msgs, self.pks)
        self.assertEqual(got, [False] + [True] * 7)

    def test_malformed_inputs_false_not_crash(self):
        sigs = list(self.sigs)
        pks = list(self.pks)
        sigs[1] = b"\x01" * 17            # bad length
        pks[2] = b"\x02" * 31             # bad length
        # non-canonical s >= L
        s_big = (ed.L + 5).to_bytes(32, "little")
        sigs[3] = self.sigs[3][:32] + s_big
        got = self.prov.verify_batch(sigs, self.msgs, pks)
        self.assertEqual(got, [True, False, False, False,
                               True, True, True, True])

    def test_agrees_with_host_verifier(self):
        got = self.prov.verify_batch(self.sigs, self.msgs, self.pks)
        want = [c.verify_signature(s, m, pk) for c, s, m, pk in
                zip(self.cryptos, self.sigs, self.msgs, self.pks)]
        self.assertEqual(got, want)

    def test_single_path_is_cofactored_host_rule(self):
        """The provider's single verify (the sub-threshold / fallback
        path) must apply the same rule as the batch relation; it accepts
        honest signatures and rejects corrupt ones like OpenSSL does."""
        self.assertTrue(self.prov.verify_signature(
            self.sigs[0], self.msgs[0], self.pks[0]))
        bad = bytearray(self.sigs[0])
        bad[1] ^= 0x01
        self.assertFalse(self.prov.verify_signature(
            bytes(bad), self.msgs[0], self.pks[0]))
        # sub-threshold batches route through it too
        small = Ed25519TpuCrypto(b"\x88" * 32, device_threshold=64)
        self.assertEqual(
            small.verify_batch(self.sigs[:3], self.msgs[:3], self.pks[:3]),
            [True] * 3)


if __name__ == "__main__":
    unittest.main()
