"""Perf ledger + soak telemetry (obs/ledger.py, obs/telemetry.py,
scripts/ledger.py): BenchRecord schema round-trip, legacy BENCH-wrapper
parsing, noise-band diff classification, plateau + regression gates
against synthetic trajectories AND the real BENCH_r01-r05 files as
fixtures, the TelemetrySampler's sample/ring/JSONL/trend surfaces, the
flight-recorder churn counters, the WAL size hooks, and the CLI's
exit-code contract."""

import asyncio
import glob
import json
import os
import subprocess
import sys
import tempfile
import unittest

from consensus_overlord_tpu.engine.wal import FileWal, MemoryWal, frame_record
from consensus_overlord_tpu.obs import FlightRecorder, Metrics
from consensus_overlord_tpu.obs import ledger
from consensus_overlord_tpu.obs.telemetry import (
    TelemetrySampler,
    wal_size_bytes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: The real r01-r05 trajectory, committed at the repo root — the ledger
#: must stay able to read its own history.
BENCH_FIXTURES = sorted(glob.glob(os.path.join(REPO, "BENCH_r0[1-5].json")))
LEDGER_CLI = os.path.join(REPO, "scripts", "ledger.py")


def rec(run, value, unit="verifies/s", metric="throughput",
        stages=None, occupancy=None):
    return ledger.BenchRecord(run=run, metric=metric, value=value,
                              unit=unit, stages=stages or {},
                              occupancy=occupancy)


class LedgerSchema(unittest.TestCase):
    def test_build_record_roundtrip(self):
        class _Prof:  # DeviceProfiler.summary() shape, no device needed
            def summary(self):
                return {"crypto_device_stage_seconds": {
                            "verify_batch/dispatch":
                                {"count": 4, "total_s": 0.8}},
                        "occupancy": 0.875}

        doc = ledger.build_record(
            "bls_verifies_per_s", 12345.6, "verifies/s", profiler=_Prof(),
            context={"batch": 8192}, vs_baseline=8.8)
        self.assertEqual(doc["ledger_version"], ledger.LEDGER_VERSION)
        self.assertIn("git_sha", doc["env"])
        loaded = ledger.load_record(json.loads(json.dumps(doc)), run="x")
        self.assertEqual(loaded.value, 12345.6)
        self.assertEqual(loaded.context["batch"], 8192)
        self.assertEqual(loaded.occupancy, 0.875)
        self.assertAlmostEqual(
            loaded.stage_means()["verify_batch/dispatch"], 0.2)
        # to_dict -> from_dict closes the loop
        again = ledger.BenchRecord.from_dict(loaded.to_dict(), run="x")
        self.assertEqual(again.value, loaded.value)
        self.assertEqual(again.stages, loaded.stages)
        self.assertEqual(again.vs_baseline, 8.8)

    def test_legacy_driver_wrapper_and_tail_mining(self):
        wrapper = {
            "n": 9, "cmd": "python bench.py", "rc": 0,
            "tail": ("WARNING: Platform 'axon' is experimental\n"
                     '{"context": {"batch": 4096, "iters": 2}}\n'
                     "not json at all\n"
                     '{"metric": "m", "value": 10.0, "unit": "u"}\n'),
            "parsed": {"metric": "m", "value": 10.0, "unit": "u",
                       "vs_baseline": 2.0},
        }
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "BENCH_r09.json")
            with open(path, "w") as f:
                json.dump(wrapper, f)
            loaded = ledger.load_record(path)
        self.assertEqual(loaded.run, "r09")     # label from the filename
        self.assertEqual(loaded.value, 10.0)
        self.assertEqual(loaded.vs_baseline, 2.0)
        self.assertEqual(loaded.context["batch"], 4096)  # mined from tail

    def test_real_bench_fixtures_load(self):
        self.assertEqual(len(BENCH_FIXTURES), 5, BENCH_FIXTURES)
        records = ledger.load_records(BENCH_FIXTURES)
        self.assertEqual([r.run for r in records],
                         ["r01", "r02", "r03", "r04", "r05"])
        self.assertAlmostEqual(records[0].value, 400.55)
        self.assertAlmostEqual(records[4].value, 20808.15)
        # r02+ tails carry the {"context": ...} stderr line
        self.assertEqual(records[4].context.get("batch"), 8192)


class DiffNoiseBands(unittest.TestCase):
    def test_throughput_classification_against_band(self):
        base = rec("a", 1000.0)
        for value, verdict in ((1030.0, "noise"), (970.0, "noise"),
                               (1100.0, "improved"), (900.0, "regressed")):
            deltas = ledger.diff(base, rec("b", value),
                                 throughput_band=0.05)
            self.assertEqual(deltas[0].verdict, verdict,
                             f"{value}: {deltas[0]}")

    def test_latency_metric_direction_flips(self):
        base = rec("a", 100.0, unit="ms", metric="round_p50_ms")
        down = ledger.diff(base, rec("b", 80.0, unit="ms",
                                     metric="round_p50_ms"))[0]
        self.assertEqual(down.verdict, "improved")
        up = ledger.diff(base, rec("b", 130.0, unit="ms",
                                   metric="round_p50_ms"))[0]
        self.assertEqual(up.verdict, "regressed")

    def test_rate_units_are_not_latencies(self):
        self.assertFalse(ledger._lower_is_better("throughput",
                                                 "verifies/s"))
        self.assertFalse(ledger._lower_is_better("commits_per_s", ""))
        self.assertTrue(ledger._lower_is_better("round_p50_ms", "ms"))
        self.assertTrue(ledger._lower_is_better("multi-chain", "wall_s"))

    def test_stage_means_compared_lower_better(self):
        stages_a = {"verify_batch/dispatch": {"count": 10, "total_s": 1.0}}
        stages_b = {"verify_batch/dispatch": {"count": 10, "total_s": 2.0}}
        deltas = ledger.diff(rec("a", 1.0, stages=stages_a),
                             rec("b", 1.0, stages=stages_b),
                             stage_band=0.25)
        stage = [d for d in deltas if d.dimension.startswith("stage ")][0]
        self.assertEqual(stage.verdict, "regressed")  # 2x the mean
        self.assertFalse(stage.higher_is_better)

    def test_occupancy_dimension(self):
        deltas = ledger.diff(rec("a", 1.0, occupancy=0.9),
                             rec("b", 1.0, occupancy=0.5))
        occ = [d for d in deltas if d.dimension == "occupancy"][0]
        self.assertEqual(occ.verdict, "regressed")


class PlateauAndCheck(unittest.TestCase):
    def test_plateau_detection_on_synthetic_trajectory(self):
        # climb, climb, flat, flat — trailing 3-record plateau
        records = [rec(f"r{i}", v) for i, v in
                   enumerate([100, 150, 200, 201, 200.5])]
        runs = ledger.plateaus(records, plateau_runs=2, plateau_band=0.01)
        self.assertEqual(runs, [(2, 4)])
        report = ledger.trend(records)
        self.assertEqual(report["plateaus"],
                         [{"from": "r2", "to": "r4", "runs": 3}])
        self.assertTrue(report["rows"][4].get("plateau"))

    def test_no_plateau_on_a_climbing_curve(self):
        records = [rec(f"r{i}", 100.0 * (1.1 ** i)) for i in range(5)]
        self.assertEqual(ledger.plateaus(records), [])

    def test_check_fails_synthetic_ten_pct_regression(self):
        findings = ledger.check([rec("prev", 20808.15),
                                 rec("cur", 18727.3)])
        fatal = [f for f in findings if f.fatal]
        self.assertEqual([f.kind for f in fatal], ["regression"])

    def test_check_passes_within_noise_and_flags_plateau(self):
        findings = ledger.check([rec("r04", 20832.38),
                                 rec("r05", 20808.15)])
        self.assertFalse(any(f.fatal for f in findings))
        self.assertEqual([f.kind for f in findings], ["plateau"])
        # the same plateau turns fatal only on request
        strict = ledger.check([rec("r04", 20832.38),
                               rec("r05", 20808.15)],
                              fail_on_plateau=True)
        self.assertTrue(any(f.fatal and f.kind == "plateau"
                            for f in strict))

    def test_check_latency_metric_regresses_upward(self):
        findings = ledger.check(
            [rec("a", 100.0, unit="ms", metric="round_p50_ms"),
             rec("b", 120.0, unit="ms", metric="round_p50_ms")])
        self.assertTrue(any(f.kind == "regression" and f.fatal
                            for f in findings))

    def test_check_stage_blowup(self):
        a = rec("a", 1000.0,
                stages={"verify_batch/readback":
                        {"count": 5, "total_s": 0.5}})
        b = rec("b", 1000.0,
                stages={"verify_batch/readback":
                        {"count": 5, "total_s": 1.0}})
        findings = ledger.check([a, b], max_stage_blowup=0.5)
        self.assertTrue(any(f.kind == "stage_blowup" and f.fatal
                            for f in findings))
        # within the blowup limit: clean
        b.stages["verify_batch/readback"]["total_s"] = 0.6
        self.assertFalse(any(f.fatal for f in ledger.check(
            [a, b], max_stage_blowup=0.5)))

    def test_incomparable_records_flag_instead_of_gating(self):
        # A glob that swept MULTICHIP (wall_s) and BENCH (verifies/s)
        # together: the six-digit-percent "regression" must not exist.
        a = rec("a", 4.2, unit="wall_s", metric="multi-chain")
        b = rec("b", 20808.15)
        findings = ledger.check([a, b])
        self.assertFalse(any(f.fatal for f in findings), findings)
        self.assertEqual(findings[0].kind, "incomparable")
        self.assertEqual(ledger.diff(a, b), [])       # nothing compared
        # and a metric change breaks a plateau run, not extends it
        flat = [rec("r1", 100.0), rec("r2", 100.1),
                rec("r3", 100.0, metric="other")]
        self.assertEqual(ledger.plateaus(flat), [(0, 1)])

    def test_real_trajectory_r04_r05_plateau_passes_gate(self):
        records = ledger.load_records(BENCH_FIXTURES)
        findings = ledger.check(records)
        self.assertFalse(any(f.fatal for f in findings), findings)
        plateau = [f for f in findings if f.kind == "plateau"]
        self.assertEqual(len(plateau), 1, findings)
        self.assertIn("r04", plateau[0].detail)
        self.assertIn("r05", plateau[0].detail)


def soak_rec(run, value=6.0, **soak):
    return ledger.BenchRecord(run=run, metric="soak-chaos-survival",
                              value=value, unit="heights/s",
                              soak=soak)


class SoakSurvivalGates(unittest.TestCase):
    """Soak BenchRecords: the "soak" block round-trips, and check()
    gates WAL-growth/RSS-slope regressions like perf regressions."""

    def test_soak_block_round_trips(self):
        doc = {"ledger_version": 1, "metric": "soak-chaos-survival",
               "value": 6.2, "unit": "heights/s",
               "soak": {"rss_slope_bytes_per_s": 1200.5,
                        "wal_growth_bytes_per_s": 88,
                        "chaos_cycles": 4,
                        "drift_ok": True,        # non-numeric: dropped
                        "note": "n/a"}}
        r = ledger.load_record(doc, run="s1")
        self.assertEqual(r.soak, {"rss_slope_bytes_per_s": 1200.5,
                                  "wal_growth_bytes_per_s": 88.0,
                                  "chaos_cycles": 4.0})
        self.assertEqual(r.to_dict()["soak"], r.soak)

    def test_check_fails_wal_growth_blowup(self):
        prev = soak_rec("s1", wal_growth_bytes_per_s=100.0,
                        rss_slope_bytes_per_s=1000.0)
        cur = soak_rec("s2", wal_growth_bytes_per_s=400.0,
                       rss_slope_bytes_per_s=1050.0)
        findings = ledger.check([prev, cur])
        drift = [f for f in findings if f.kind == "soak_drift"]
        self.assertEqual(len(drift), 1, findings)
        self.assertTrue(drift[0].fatal)
        self.assertIn("wal_growth_bytes_per_s", drift[0].detail)

    def test_check_passes_within_soak_band(self):
        prev = soak_rec("s1", rss_slope_bytes_per_s=1000.0,
                        flightrec_drop_per_s=50.0)
        cur = soak_rec("s2", rss_slope_bytes_per_s=1400.0,
                       flightrec_drop_per_s=60.0)  # +40% < 50% band
        self.assertFalse(any(f.kind == "soak_drift"
                             for f in ledger.check([prev, cur])))

    def test_commit_rate_gates_downward(self):
        # higher-is-better dim: a collapse in commit rate is fatal
        prev = soak_rec("s1", commit_rate_heights_per_s=6.0)
        cur = soak_rec("s2", commit_rate_heights_per_s=2.0)
        findings = ledger.check([prev, cur])
        self.assertTrue(any(f.kind == "soak_drift" and f.fatal
                            for f in findings), findings)

    def test_zero_baseline_gates_nothing(self):
        prev = soak_rec("s1", wal_growth_bytes_per_s=0.0)
        cur = soak_rec("s2", wal_growth_bytes_per_s=50.0)
        self.assertFalse(any(f.kind == "soak_drift"
                             for f in ledger.check([prev, cur])))

    def test_diff_classifies_soak_dims(self):
        prev = soak_rec("s1", rss_slope_bytes_per_s=1000.0)
        cur = soak_rec("s2", rss_slope_bytes_per_s=2000.0)
        deltas = {d.dimension: d.verdict
                  for d in ledger.diff(prev, cur)}
        self.assertEqual(deltas.get("soak rss_slope_bytes_per_s"),
                         "regressed", deltas)


class DriftCheckGate(unittest.TestCase):
    """obs/telemetry.py drift_check: the soak-chaos lane's pure gate."""

    TREND = {"samples": 20, "span_s": 300.0,
             "rss_slope_bytes_per_s": 1_000_000.0,
             "wal_growth_bytes_per_s": 2_048.0,
             "flightrec_drop_per_s": 120.0,
             "compile_cache_hit_ratio": 0.9}

    def test_healthy_trend_passes_defaults(self):
        from consensus_overlord_tpu.obs.telemetry import drift_check

        self.assertEqual(drift_check(self.TREND), [])

    def test_each_ceiling_trips_its_own_violation(self):
        from consensus_overlord_tpu.obs.telemetry import drift_check

        out = drift_check(self.TREND,
                          {"max_rss_slope_bytes_per_s": 500_000})
        self.assertEqual(len(out), 1)
        self.assertIn("RSS slope", out[0])
        out = drift_check(self.TREND,
                          {"max_wal_growth_bytes_per_s": 1_000})
        self.assertIn("WAL growth", out[0])
        out = drift_check(self.TREND,
                          {"max_flightrec_drop_per_s": 100})
        self.assertIn("drop rate", out[0])
        out = drift_check(self.TREND,
                          {"min_compile_cache_hit_ratio": 0.95})
        self.assertIn("hit ratio", out[0])

    def test_disabled_and_absent_dims_gate_nothing(self):
        from consensus_overlord_tpu.obs.telemetry import drift_check

        self.assertEqual(drift_check(
            self.TREND, {"max_rss_slope_bytes_per_s": None}), [])
        sparse = {"samples": 5, "span_s": 30.0}  # no rates collected
        self.assertEqual(drift_check(sparse), [])

    def test_too_few_samples_is_itself_a_violation(self):
        from consensus_overlord_tpu.obs.telemetry import drift_check

        out = drift_check({"samples": 1})
        self.assertEqual(len(out), 1)
        self.assertIn("too few samples", out[0])


class LedgerCLI(unittest.TestCase):
    """scripts/ledger.py exit-code contract (stdlib-only subprocesses —
    no jax import, so each run is interpreter-startup cheap)."""

    def _run(self, *argv):
        return subprocess.run([sys.executable, LEDGER_CLI, *argv],
                              capture_output=True, text=True, cwd=REPO)

    def test_trend_prints_trajectory_and_flags_plateau(self):
        out = self._run("trend", *BENCH_FIXTURES)
        self.assertEqual(out.returncode, 0, out.stderr)
        self.assertIn("r01", out.stdout)
        self.assertIn("PLATEAU: r04 -> r05", out.stdout)

    def test_check_exit_codes(self):
        ok = self._run("check", *BENCH_FIXTURES)
        self.assertEqual(ok.returncode, 0, ok.stdout + ok.stderr)
        with tempfile.TemporaryDirectory() as td:
            synth = os.path.join(td, "BENCH_r06.json")
            with open(synth, "w") as f:
                json.dump({"ledger_version": 1,
                           "metric": "bls12381_sig_verifies_per_sec"
                                     "_per_chip",
                           "value": 20808.15 * 0.9, "unit": "verifies/s"},
                          f)
            bad = self._run("check", BENCH_FIXTURES[-1], synth)
            self.assertEqual(bad.returncode, 1, bad.stdout + bad.stderr)
            self.assertIn("regression", bad.stdout)


class FlightRecorderChurn(unittest.TestCase):
    def test_dropped_counts_ring_evictions(self):
        ring = FlightRecorder(capacity=4)
        for i in range(6):
            ring.record("tick", i=i)
        self.assertEqual(len(ring), 4)
        self.assertEqual(ring.recorded, 6)
        self.assertEqual(ring.dropped, 2)
        self.assertEqual(ring.stats(),
                         {"events": 4, "capacity": 4,
                          "recorded": 6, "dropped": 2})


class WalSizeHook(unittest.TestCase):
    def test_memory_wal_size_tracks_framed_blob(self):
        wal = MemoryWal()
        self.assertEqual(wal.size_bytes(), 0)
        asyncio.run(wal.save(b"state-blob"))
        self.assertEqual(wal.size_bytes(), len(frame_record(b"state-blob")))
        self.assertEqual(wal_size_bytes(wal), wal.size_bytes())

    def test_file_wal_size_tracks_disk(self):
        with tempfile.TemporaryDirectory() as td:
            wal = FileWal(td)
            self.assertEqual(wal.size_bytes(), 0)
            asyncio.run(wal.save(b"abcdef"))
            self.assertEqual(wal.size_bytes(),
                             len(frame_record(b"abcdef")))

    def test_hookless_objects_report_none(self):
        self.assertIsNone(wal_size_bytes(object()))


class TelemetrySamplerTests(unittest.TestCase):
    def _sampler(self, **kw):
        metrics = Metrics()
        wal = MemoryWal()
        ring = FlightRecorder(capacity=4)
        sampler = TelemetrySampler(
            metrics=metrics, interval_s=60.0,
            wal_size_fn=lambda: wal_size_bytes(wal),
            recorders_fn=lambda: [ring],
            breaker_status_fn=lambda: {"state": "closed"}, **kw)
        return sampler, metrics, wal, ring

    def test_sample_fields(self):
        sampler, metrics, wal, ring = self._sampler()
        asyncio.run(wal.save(b"x" * 100))
        for i in range(6):
            ring.record("e", i=i)
        metrics.committed_heights.inc(3)
        doc = sampler.sample_now()
        self.assertGreater(doc["rss_bytes"], 0)
        self.assertEqual(doc["wal_bytes"], wal.size_bytes())
        self.assertEqual(doc["flightrec"],
                         {"events": 4, "recorded": 6, "dropped": 2})
        self.assertEqual(doc["breaker"]["state"], "closed")
        self.assertIn("compile_cache", doc)
        self.assertEqual(
            doc["counters"]["consensus_committed_heights_total"], 3.0)

    def test_ring_bounded_and_jsonl_written(self):
        with tempfile.TemporaryDirectory() as td:
            out = os.path.join(td, "soak.jsonl")
            sampler, _, _, _ = self._sampler(window=4, out_path=out)
            for _ in range(6):
                sampler.sample_now()
            self.assertEqual(len(sampler.tail()), 4)       # ring bound
            self.assertEqual(sampler.samples_taken, 6)
            with open(out) as f:
                lines = [json.loads(line) for line in f]
            self.assertEqual(len(lines), 6)                # all landed
            self.assertEqual(lines[0]["seq"], 1)

    def test_jsonl_file_bounded_by_rewrite(self):
        with tempfile.TemporaryDirectory() as td:
            out = os.path.join(td, "soak.jsonl")
            sampler, _, _, _ = self._sampler(window=3, out_path=out,
                                             max_file_samples=4)
            for _ in range(7):
                sampler.sample_now()
            with open(out) as f:
                lines = [json.loads(line) for line in f]
            # capped: rewritten from the 3-sample ring at overflow, then
            # appends resume — never back above the cap + window
            self.assertLessEqual(len(lines), 4 + 3)
            self.assertEqual(lines[-1]["seq"], 7)  # newest survives

    def test_trend_deltas_over_window(self):
        sampler, metrics, wal, ring = self._sampler()
        sampler.sample_now()
        asyncio.run(wal.save(b"y" * 500))
        for i in range(10):
            ring.record("e", i=i)
        metrics.committed_heights.inc(5)
        sampler.sample_now()
        trend = sampler.trend()
        self.assertEqual(trend["samples"], 2)
        self.assertEqual(trend["wal_delta_bytes"], wal.size_bytes())
        self.assertEqual(trend["flightrec_recorded_delta"], 10)
        self.assertEqual(trend["flightrec_dropped_delta"], 6)
        self.assertIn("consensus_committed_heights_total_per_s",
                      trend["counter_rates"])
        self.assertIn("last", trend)

    def test_background_thread_and_statusz_trend_section(self):
        sampler, metrics, _, _ = self._sampler()
        sampler.interval_s = 0.05
        metrics.add_status_source("trend", sampler.trend)
        sampler.start()
        try:
            import time
            time.sleep(0.12)
        finally:
            sampler.stop()
        # immediate baseline + >=1 periodic + final stop() sample
        self.assertGreaterEqual(sampler.samples_taken, 3)
        doc = metrics.statusz()
        self.assertGreaterEqual(doc["trend"]["samples"], 1)
        self.assertIn("rss_delta_bytes", doc["trend"])
        # stop() is idempotent and start() restarts cleanly
        sampler.stop()

    def test_occupancy_omitted_until_first_batch(self):
        sampler, metrics, _, _ = self._sampler()
        # never-set gauge (initial 0.0) must not fabricate a reading
        self.assertNotIn("occupancy", sampler.sample_now())
        metrics.device_batch_occupancy.set(0.875)
        self.assertEqual(sampler.sample_now()["occupancy"], 0.875)

    def test_sampler_never_raises_on_broken_collectors(self):
        sampler = TelemetrySampler(
            wal_size_fn=lambda: 1 / 0,
            recorders_fn=lambda: 1 / 0,
            breaker_status_fn=lambda: 1 / 0)
        doc = sampler.sample_now()  # collectors explode, sample survives
        self.assertNotIn("wal_bytes", doc)
        self.assertNotIn("flightrec", doc)
        self.assertIn("ts", doc)


if __name__ == "__main__":
    unittest.main()
