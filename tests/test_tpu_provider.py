"""TpuBlsCrypto (device-batched provider) against the CPU oracle provider.

device_threshold=1 forces every path through the device kernels even at
test-sized batches (they pad to 8 lanes)."""

import pytest

from consensus_overlord_tpu.crypto.provider import CpuBlsCrypto, CryptoError
from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
from consensus_overlord_tpu.core.sm3 import sm3_hash

N = 6
KEYS = [0x1111 * (i + 1) + 7 for i in range(N)]


@pytest.fixture(scope="module")
def cpus():
    return [CpuBlsCrypto(k) for k in KEYS]


@pytest.fixture(scope="module")
def tpu(cpus):
    t = TpuBlsCrypto(KEYS[0], device_threshold=1)
    t.update_pubkeys([c.pub_key for c in cpus])
    return t


def make_votes(cpus, msg=b"block-hash-1"):
    h = sm3_hash(msg)
    return ([c.sign(h) for c in cpus], [h] * len(cpus),
            [c.pub_key for c in cpus])


class TestVerifyBatch:
    def test_all_valid(self, cpus, tpu):
        sigs, hashes, voters = make_votes(cpus)
        assert tpu.verify_batch(sigs, hashes, voters) == [True] * N

    def test_mixed_messages(self, cpus, tpu):
        h1, h2 = sm3_hash(b"m1"), sm3_hash(b"m2")
        sigs = [c.sign(h1) for c in cpus[:3]] + [c.sign(h2) for c in cpus[3:]]
        hashes = [h1] * 3 + [h2] * (N - 3)
        voters = [c.pub_key for c in cpus]
        assert tpu.verify_batch(sigs, hashes, voters) == [True] * N

    def test_bad_lane_localized(self, cpus, tpu):
        sigs, hashes, voters = make_votes(cpus)
        sigs[2] = cpus[2].sign(sm3_hash(b"other"))  # valid point, wrong msg
        got = tpu.verify_batch(sigs, hashes, voters)
        assert got == [True, True, False, True, True, True]

    def test_malformed_sig_and_bad_voter(self, cpus, tpu):
        sigs, hashes, voters = make_votes(cpus)
        sigs[0] = b"\x00" * 48           # compressed flag missing
        sigs[1] = b"short"
        voters[3] = b"\x01" * 96         # not a valid pubkey encoding
        got = tpu.verify_batch(sigs, hashes, voters)
        assert got == [False, False, True, False, True, True]

    def test_wrong_signer(self, cpus, tpu):
        sigs, hashes, voters = make_votes(cpus)
        sigs[4], sigs[5] = sigs[5], sigs[4]  # swapped: wrong keys
        got = tpu.verify_batch(sigs, hashes, voters)
        assert got == [True, True, True, True, False, False]

    def test_matches_cpu_provider(self, cpus, tpu):
        sigs, hashes, voters = make_votes(cpus, b"cross-check")
        want = [cpus[0].verify_signature(s, h, v)
                for s, h, v in zip(sigs, hashes, voters)]
        assert tpu.verify_batch(sigs, hashes, voters) == want


class TestAggregate:
    def test_aggregate_matches_oracle(self, cpus, tpu):
        h = sm3_hash(b"agg")
        sigs = [c.sign(h) for c in cpus]
        voters = [c.pub_key for c in cpus]
        assert (tpu.aggregate_signatures(sigs, voters) ==
                cpus[0].aggregate_signatures(sigs, voters))

    def test_aggregate_rejects_garbage(self, cpus, tpu):
        h = sm3_hash(b"agg")
        sigs = [c.sign(h) for c in cpus]
        sigs[1] = b"\xff" * 48
        with pytest.raises(CryptoError):
            tpu.aggregate_signatures(sigs, [c.pub_key for c in cpus])

    def test_length_mismatch(self, tpu, cpus):
        with pytest.raises(CryptoError):
            tpu.aggregate_signatures([b"x"], [c.pub_key for c in cpus])

    def test_verify_aggregated(self, cpus, tpu):
        h = sm3_hash(b"qc")
        voters = [c.pub_key for c in cpus]
        agg = cpus[0].aggregate_signatures([c.sign(h) for c in cpus], voters)
        assert tpu.verify_aggregated_signature(agg, h, voters)
        assert not tpu.verify_aggregated_signature(agg, sm3_hash(b"no"), voters)
        # subset of voters ⇒ aggregate over full set must fail
        assert not tpu.verify_aggregated_signature(agg, h, voters[:4])

    def test_verify_aggregated_bad_voter(self, cpus, tpu):
        h = sm3_hash(b"qc2")
        voters = [c.pub_key for c in cpus]
        agg = cpus[0].aggregate_signatures([c.sign(h) for c in cpus], voters)
        bad_voters = voters[:-1] + [b"\x02" * 96]
        assert not tpu.verify_aggregated_signature(agg, h, bad_voters)


class TestProviderSurface:
    def test_sign_verify_roundtrip(self, tpu):
        h = sm3_hash(b"single")
        sig = tpu.sign(h)
        assert tpu.verify_signature(sig, h, tpu.pub_key)
        assert not tpu.verify_signature(sig, sm3_hash(b"x"), tpu.pub_key)

    def test_hash_is_sm3(self, tpu):
        assert tpu.hash(b"abc") == sm3_hash(b"abc")


class TestSubgroupAttack:
    def test_order3_component_rejected_deterministically(self, cpus, tpu):
        """sig' = sig + T with T = (0, 2) the order-3 cofactor point: the
        canonical attack against batched-by-linearity subgroup checks
        (the residual r·(φ(T)−[λ]T) lives in Z/3 and cancels for 1/3 of
        random weights — and relation-side r·T cancels with it, so a
        linearity-batched checker ACCEPTS the rogue lane whenever the
        subgroup residual misses).  The per-lane device check must
        reject it on EVERY run — repeat to catch a probabilistic
        accept."""
        from consensus_overlord_tpu.crypto import bls12381 as oracle

        sigs, hashes, voters = make_votes(cpus, b"torsion")
        t = (0, 2)
        assert oracle.g1_add(oracle.g1_add(t, t), t) is None  # order 3
        rogue_pt = oracle.g1_add(oracle.g1_decompress(sigs[3]), t)
        assert not oracle.g1_in_subgroup(rogue_pt)
        sigs[3] = oracle.g1_compress(rogue_pt)
        for _ in range(6):  # fresh random weights each attempt
            got = tpu.verify_batch(sigs, hashes, voters)
            assert got == [True, True, True, False, True, True]

    def test_non_subgroup_signature_lane_rejected(self, cpus, tpu):
        """An on-curve G1 point OUTSIDE the r-torsion subgroup (generic
        cofactor component) must fail without poisoning honest lanes."""
        from consensus_overlord_tpu.crypto import bls12381 as oracle

        x = 7
        pt = None
        while pt is None:
            rhs = (pow(x, 3, oracle.P) + 4) % oracle.P
            y = pow(rhs, (oracle.P + 1) // 4, oracle.P)
            if y * y % oracle.P == rhs:
                cand = (x, y)
                if not oracle.g1_in_subgroup(cand):
                    pt = cand
            x += 1
        rogue = oracle.g1_compress(pt)

        sigs, hashes, voters = make_votes(cpus)
        sigs[4] = rogue
        got = tpu.verify_batch(sigs, hashes, voters)
        assert got == [True, True, True, True, False, True]

    def test_all_honest_subgroup_check_passes(self, cpus, tpu):
        """Sanity twin: with honest lanes the aggregate check must NOT
        fire (no silent fallback-to-host on the hot path)."""
        sigs, hashes, voters = make_votes(cpus, msg=b"block-hash-sub")
        assert tpu.verify_batch(sigs, hashes, voters) == [True] * N


class TestAsyncPipeline:
    def test_async_matches_sync_and_pipelines(self, cpus, tpu):
        """verify_batch_async: two in-flight batches resolve in order to
        the same verdicts as the sync path (incl. a bad lane)."""
        sigs1, hashes1, voters1 = make_votes(cpus, b"pipe-a")
        sigs2, hashes2, voters2 = make_votes(cpus, b"pipe-b")
        sigs2[1] = cpus[1].sign(sm3_hash(b"wrong"))
        r1 = tpu.verify_batch_async(sigs1, hashes1, voters1)
        r2 = tpu.verify_batch_async(sigs2, hashes2, voters2)
        assert r1() == [True] * N
        assert r2() == [True, False, True, True, True, True]

    def test_async_multi_hash_fused(self, cpus, tpu):
        """2–4 distinct hashes dispatch as ONE fused multi-group kernel
        (no silent degradation to a blocking path)."""
        h1, h2 = sm3_hash(b"x1"), sm3_hash(b"x2")
        sigs = [c.sign(h1) for c in cpus[:3]] + [c.sign(h2) for c in cpus[3:]]
        hashes = [h1] * 3 + [h2] * (N - 3)
        voters = [c.pub_key for c in cpus]
        assert tpu.verify_batch_async(sigs, hashes, voters)() == [True] * N

    def test_async_multi_hash_fused_bad_lane(self, cpus, tpu):
        h1, h2, h3 = sm3_hash(b"y1"), sm3_hash(b"y2"), sm3_hash(b"y3")
        hashes = [h1, h1, h2, h2, h3, h3]
        sigs = [c.sign(h) for c, h in zip(cpus, hashes)]
        sigs[3] = cpus[3].sign(sm3_hash(b"evil"))
        voters = [c.pub_key for c in cpus]
        got = tpu.verify_batch_async(sigs, hashes, voters)()
        assert got == [True, True, True, False, True, True]

    def test_async_many_hashes_split(self, cpus, tpu):
        """>4 distinct hashes split into pipelined single-hash
        sub-batches, resolved back into lane order."""
        hashes = [sm3_hash(b"z%d" % i) for i in range(N)]
        sigs = [c.sign(h) for c, h in zip(cpus, hashes)]
        voters = [c.pub_key for c in cpus]
        assert tpu.verify_batch_async(sigs, hashes, voters)() == [True] * N
        sigs[5] = cpus[5].sign(sm3_hash(b"evil"))
        got = tpu.verify_batch_async(sigs, hashes, voters)()
        assert got == [True] * 5 + [False]

    def test_async_aggregate_and_verify_aggregated(self, cpus, tpu):
        """The QC-path async forms dispatch now and resolve later to the
        same results as the sync forms (engine awaits these off-loop)."""
        sigs, hashes, voters = make_votes(cpus, b"qc-async")
        r_agg = tpu.aggregate_signatures_async(sigs, voters)
        agg = r_agg()
        assert agg == tpu.aggregate_signatures(sigs, voters)
        r_ok = tpu.verify_aggregated_async(agg, hashes[0], voters)
        r_bad = tpu.verify_aggregated_async(agg, sm3_hash(b"no"), voters)
        assert r_ok() is True
        assert r_bad() is False


class TestThresholdKnobs:
    def test_pad_min_floor(self, monkeypatch):
        """CONSENSUS_PAD_MIN pins the bottom of the pad ladder so a
        deployment compiles one kernel shape (cold compiles through the
        remote relay cost tens of minutes per rung)."""
        from consensus_overlord_tpu.crypto.tpu_provider import _pad_to
        monkeypatch.delenv("CONSENSUS_PAD_MIN", raising=False)
        assert _pad_to(5) == 8
        assert _pad_to(33) == 128
        monkeypatch.setenv("CONSENSUS_PAD_MIN", "32")
        assert _pad_to(5) == 32
        assert _pad_to(32) == 32
        assert _pad_to(33) == 128
        monkeypatch.setenv("CONSENSUS_PAD_MIN", "8192")
        assert _pad_to(5) == 8192
        monkeypatch.setenv("CONSENSUS_PAD_MIN", "9000")
        assert _pad_to(5) == 16384  # above the ladder: multiple of top

    def test_pad_ladder_has_4096_rung(self):
        """A 4096-lane batch must not pay the 8192 kernel (2x the MSM
        work — the rung was missing through r4)."""
        from consensus_overlord_tpu.crypto.tpu_provider import _pad_to
        assert _pad_to(2049) == 4096
        assert _pad_to(4096) == 4096
        assert _pad_to(4097) == 8192

    def test_pk_capacity_floor(self, monkeypatch):
        """CONSENSUS_PK_CAP_MIN pins the pubkey-cache capacity ladder —
        the cache's row count is part of every kernel's shape, so a
        capacity crossing is a full kernel-set recompile."""
        from consensus_overlord_tpu.crypto.tpu_provider import _pk_capacity
        monkeypatch.delenv("CONSENSUS_PK_CAP_MIN", raising=False)
        assert _pk_capacity(10) == 256
        assert _pk_capacity(257) == 1024
        monkeypatch.setenv("CONSENSUS_PK_CAP_MIN", "16384")
        assert _pk_capacity(10) == 16384
        assert _pk_capacity(16384) == 16384
        monkeypatch.setenv("CONSENSUS_PK_CAP_MIN", "20000")
        assert _pk_capacity(10) == 32768  # above the ladder: multiple of top

    def test_qc_threshold_splits_paths(self, cpus):
        """qc_device_threshold routes the QC paths (aggregate / verify
        aggregated / pubkey validation) independently of the verify
        threshold — small fleets want verifies batched on device but QC
        work on the host (one decompress + N adds + 2 pairings)."""
        t = TpuBlsCrypto(KEYS[0], device_threshold=1,
                         qc_device_threshold=10**9)
        t.update_pubkeys([c.pub_key for c in cpus])  # host-validated
        sigs, hashes, voters = make_votes(cpus, b"split-thresh")
        # verify path: device (threshold 1); QC paths: host (threshold inf)
        assert t.verify_batch(sigs, hashes, voters) == [True] * N
        agg = t.aggregate_signatures(sigs, voters)
        assert agg == CpuBlsCrypto(KEYS[0]).aggregate_signatures(
            sigs, voters)
        assert t.verify_aggregated_signature(agg, hashes[0], voters)
