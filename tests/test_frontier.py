"""Batching frontier: coalescing, correctness, and end-to-end consensus."""

import asyncio

import pytest

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto.frontier import (
    BatchingVerifier, signature_claims)
from consensus_overlord_tpu.crypto.provider import (
    default_sim_crypto_class,
    sim_crypto,
)
from consensus_overlord_tpu.sim.harness import SimNetwork


def run(coro):
    return asyncio.run(coro)


class CountingProvider(default_sim_crypto_class()):
    """Sim provider (Ed25519 when `cryptography` is importable) that
    records verify_batch call sizes."""

    def __init__(self, seed):
        super().__init__(seed)
        self.batch_sizes = []

    def verify_batch(self, sigs, hashes, voters):
        self.batch_sizes.append(len(sigs))
        return super().verify_batch(sigs, hashes, voters)


class TestBatching:
    def test_concurrent_requests_coalesce(self):
        async def go():
            prov = CountingProvider(b"\x01" * 32)
            h = sm3_hash(b"m")
            sig = prov.sign(h)
            fr = BatchingVerifier(prov, max_batch=64, linger_s=0.01)
            results = await asyncio.gather(
                *(fr.verify(sig, h, prov.pub_key) for _ in range(20)))
            assert all(results)
            assert prov.batch_sizes == [20]
            assert fr.stats.batches == 1 and fr.stats.requests == 20
        run(go())

    def test_max_batch_flushes_immediately(self):
        async def go():
            prov = CountingProvider(b"\x02" * 32)
            h = sm3_hash(b"m")
            sig = prov.sign(h)
            fr = BatchingVerifier(prov, max_batch=8, linger_s=10.0)
            results = await asyncio.gather(
                *(fr.verify(sig, h, prov.pub_key) for _ in range(8)))
            assert all(results)  # would hang for 10s if linger were waited
            assert prov.batch_sizes == [8]
        run(go())

    def test_bad_signatures_fail_individually(self):
        async def go():
            prov = CountingProvider(b"\x03" * 32)
            other = sim_crypto(b"\x04" * 32)
            h = sm3_hash(b"m")
            good, bad = prov.sign(h), other.sign(h)
            fr = BatchingVerifier(prov, max_batch=64, linger_s=0.005)
            r = await asyncio.gather(
                fr.verify(good, h, prov.pub_key),
                fr.verify(bad, h, prov.pub_key),
                fr.verify(b"garbage", h, prov.pub_key))
            assert r == [True, False, False]
            assert fr.stats.failures == 2
        run(go())

    def test_provider_exception_degrades_to_false(self):
        class Exploding:
            def verify_batch(self, *a):
                raise RuntimeError("device on fire")

        async def go():
            fr = BatchingVerifier(Exploding(), max_batch=4, linger_s=0.001)
            assert await fr.verify(b"s", b"h", b"v") is False
        run(go())


class TestClaims:
    def test_signature_claims_cover_wire_types(self):
        from consensus_overlord_tpu.core.types import (
            Choke, Proposal, SignedChoke, SignedProposal, SignedVote, Status,
            Vote, VoteType)
        p = Proposal(1, 0, b"c", sm3_hash(b"c"), None, b"me")
        sp = SignedProposal(p, b"sig")
        assert signature_claims(sp) == (b"sig", sm3_hash(p.encode()), b"me")
        v = Vote(1, 0, VoteType.PREVOTE, sm3_hash(b"c"))
        sv = SignedVote(b"voter", b"sig2", v)
        assert signature_claims(sv) == (b"sig2", sm3_hash(v.encode()), b"voter")
        c = Choke(1, 0)
        sc = SignedChoke(b"sig3", b"addr", c)
        assert signature_claims(sc) == (b"sig3", sm3_hash(c.encode()), b"addr")
        assert signature_claims(Status(1, 3000, None, [])) is None


class TestEndToEnd:
    def test_consensus_with_frontier(self):
        async def go():
            net = SimNetwork(n_validators=4, block_interval_ms=50,
                             use_frontier=True, frontier_linger_s=0.001)
            net.start()
            await net.run_until_height(5, timeout=30.0)
            await net.stop()
            stats = [n.frontier.stats for n in net.nodes]
            assert sum(s.requests for s in stats) > 0
            assert all(s.failures == 0 for s in stats)
        run(go())
