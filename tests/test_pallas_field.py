"""Pallas field-mul kernel vs the XLA FieldSpec path (interpret mode on
the CPU test mesh; the same kernel compiles via Mosaic on real TPU —
scripts/bench_pallas.py measures it there)."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from consensus_overlord_tpu.ops.field import BLS12_381_FQ as FQ  # noqa: E402
from consensus_overlord_tpu.ops.pallas_field import (  # noqa: E402
    PallasField, mul_transposed)


def _rand_field(rng, b):
    return [int.from_bytes(rng.bytes(47), "big") for _ in range(b)]


def test_mul_transposed_matches_xla():
    rng = np.random.default_rng(3)
    b = 256
    x = jnp.asarray(FQ.from_ints(_rand_field(rng, b)))
    y = jnp.asarray(FQ.from_ints(_rand_field(rng, b)))
    want = FQ.to_ints(FQ.mul(x, y))
    mul = mul_transposed(FQ)
    got_t = mul(jnp.moveaxis(x, 0, 1), jnp.moveaxis(y, 0, 1))
    assert FQ.to_ints(jnp.moveaxis(got_t, 0, 1)) == want


def test_pallas_field_facade():
    rng = np.random.default_rng(4)
    b = 100  # not a block multiple: exercises the pad/slice path
    x = jnp.asarray(FQ.from_ints(_rand_field(rng, b)))
    y = jnp.asarray(FQ.from_ints(_rand_field(rng, b)))
    pf = PallasField(FQ)
    assert FQ.to_ints(pf.mul(x, y)) == FQ.to_ints(FQ.mul(x, y))
    assert FQ.to_ints(pf.sq(x)) == FQ.to_ints(FQ.sq(x))
    # non-mul surface delegates to the wrapped spec
    assert pf.n == FQ.n and pf.p == FQ.p


def test_edge_values():
    vals = [0, 1, FQ.p - 1, FQ.p - 2, 2**380]
    x = jnp.asarray(FQ.from_ints(vals))
    y = jnp.asarray(FQ.from_ints(list(reversed(vals))))
    pf = PallasField(FQ)
    assert FQ.to_ints(pf.mul(x, y)) == FQ.to_ints(FQ.mul(x, y))


def test_curve_ops_over_pallas_field():
    """A complete-addition point op with the Pallas multiplier matches
    the standard G1 ops — the CONSENSUS_PALLAS=1 integration path."""
    from consensus_overlord_tpu.ops import bls12381_groups as dev
    from consensus_overlord_tpu.ops.curve import CurveOps

    pf = PallasField(FQ)
    g1p = CurveOps(pf, lambda x: pf.mul_small(x, 12), "g1_pallas")
    p = dev.g1_generator(batch=4)
    wx, wy, winf = dev.G1.to_affine(dev.G1.add(p, dev.G1.dbl(p)))
    gx, gy, ginf = g1p.to_affine(g1p.add(p, g1p.dbl(p)))
    assert FQ.to_ints(wx) == FQ.to_ints(gx)
    assert FQ.to_ints(wy) == FQ.to_ints(gy)
    assert np.asarray(winf).tolist() == np.asarray(ginf).tolist()
