"""Device pairing stack (ops/fq6.py, ops/fq12.py, ops/pairing.py) vs the
host tower in crypto/bls12381.py, and the TpuBlsCrypto wiring that makes
the host oracle the fallback/cross-check twin.

Layout of the comparisons:

* Tower arithmetic (Fq6/Fq12 mul/square/inverse/frobenius/cyclotomic)
  must match the host functions value-for-value on random vectors.
* The device Miller loop runs on the twist with dropped subfield
  factors, so its raw value differs from the host `miller_loop` — but
  after ANY full final exponentiation (the host naive chain included)
  the two agree exactly, and that is what's pinned here.
* Multi-pairing verdicts must be bit-identical to
  `multi_pairing_is_one` across valid AND invalid sets — the device
  kernel is the production verdict now, the host oracle the twin.

PAIRING_TEST_VECTORS scales the randomized verdict sweep (the r06
acceptance runs the slow-marked 256-vector form on the CPU lane).
"""

import os
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.crypto.provider import CpuBlsCrypto
from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
from consensus_overlord_tpu.obs.prof import DeviceProfiler
from consensus_overlord_tpu.ops import pairing as pr

FQ2, FQ6, FQ12 = pr.FQ2, pr.FQ6, pr.FQ12

_R = random.Random(0xF12)


def rand_fq2():
    return (_R.getrandbits(380) % oracle.P, _R.getrandbits(380) % oracle.P)


def rand_fq6():
    return (rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12():
    return (rand_fq6(), rand_fq6())


def cyclotomic(a):
    """Project a into the cyclotomic subgroup host-side (the easy part
    of the final exponentiation): a^((p⁶−1)(p²+1))."""
    m = oracle.fq12_mul(oracle.fq12_conj(a), oracle.fq12_inv(a))
    return oracle.fq12_mul(
        oracle.fq12_frobenius(oracle.fq12_frobenius(m)), m)


class TestFq6:
    """Device Fq6 vs host fq6_* on random vectors (batched)."""

    def test_mul_sq(self):
        vals = [(rand_fq6(), rand_fq6()) for _ in range(4)]
        xs = FQ6.from_int_triples([a for a, _ in vals])
        ys = FQ6.from_int_triples([b for _, b in vals])
        got = FQ6.to_int_triples(jax.jit(FQ6.mul)(xs, ys))
        assert got == [oracle.fq6_mul(a, b) for a, b in vals]
        got_sq = FQ6.to_int_triples(jax.jit(FQ6.sq)(xs))
        assert got_sq == [oracle.fq6_mul(a, a) for a, _ in vals]

    def test_sparse_muls_match_dense(self):
        a = rand_fq6()
        b0, b1 = rand_fq2(), rand_fq2()
        xs = FQ6.from_int_triples([a])
        b0d = FQ2.from_ints([b0])
        b1d = FQ2.from_ints([b1])
        got01 = FQ6.to_int_triples(
            jax.jit(FQ6.mul_by_01)(xs, b0d, b1d))[0]
        assert got01 == oracle.fq6_mul(a, (b0, b1, (0, 0)))
        got1 = FQ6.to_int_triples(jax.jit(FQ6.mul_by_1)(xs, b1d))[0]
        assert got1 == oracle.fq6_mul(a, ((0, 0), b1, (0, 0)))

    def test_inv(self):
        vals = [rand_fq6() for _ in range(3)]
        xs = FQ6.from_int_triples(vals)
        got = FQ6.to_int_triples(jax.jit(FQ6.inv)(xs))
        assert got == [oracle.fq6_inv(a) for a in vals]


class TestFq12:
    """Device Fq12 vs host fq12_* on random vectors."""

    def test_mul_sq_conj_inv(self):
        a, b = rand_fq12(), rand_fq12()
        xs = FQ12.from_int_pairs([a])
        ys = FQ12.from_int_pairs([b])
        assert FQ12.to_int_pairs(
            jax.jit(FQ12.mul)(xs, ys))[0] == oracle.fq12_mul(a, b)
        assert FQ12.to_int_pairs(
            jax.jit(FQ12.sq)(xs))[0] == oracle.fq12_sq(a)
        assert FQ12.to_int_pairs(
            jax.jit(FQ12.conj)(xs))[0] == oracle.fq12_conj(a)
        assert FQ12.to_int_pairs(
            jax.jit(FQ12.inv)(xs))[0] == oracle.fq12_inv(a)

    def test_frobenius(self):
        a = rand_fq12()
        xs = FQ12.from_int_pairs([a])
        assert FQ12.to_int_pairs(jax.jit(FQ12.frobenius)(xs))[0] == \
            oracle.fq12_frobenius(a)

    def test_cyclotomic_square_and_pow(self):
        m = cyclotomic(rand_fq12())
        xs = FQ12.from_int_pairs([m])
        # Unitary squaring must agree with the generic square there.
        assert FQ12.to_int_pairs(jax.jit(FQ12.cyc_sq)(xs))[0] == \
            oracle.fq12_sq(m)
        e = 0xD201000000010000  # |x| — the final-exp chain's exponent
        got = FQ12.to_int_pairs(
            jax.jit(lambda v: FQ12.cyc_pow_abs(v, e))(xs))[0]
        assert got == oracle._cyc_pow(m, e)

    def test_mul_by_014_matches_dense(self):
        a = rand_fq12()
        c0, c1, c4 = rand_fq2(), rand_fq2(), rand_fq2()
        sparse = ((c0, c1, (0, 0)), ((0, 0), c4, (0, 0)))
        xs = FQ12.from_int_pairs([a])
        got = FQ12.to_int_pairs(jax.jit(FQ12.mul_by_014)(
            xs, FQ2.from_ints([c0]), FQ2.from_ints([c1]),
            FQ2.from_ints([c4])))[0]
        assert got == oracle.fq12_mul(a, sparse)


def _vote(sk, msg):
    h = sm3_hash(msg)
    sig = oracle.g1_decompress(oracle.sign(sk, h))
    pk = oracle.g2_decompress(oracle.sk_to_pk(sk))
    return sig, pk, oracle.hash_to_g1(h, b"")


NEG_G2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))


def _device_miller_one_pair(p_pt, q_pt):
    """Miller value of ONE pair through the production rung-2 kernel
    (second lane masked off), read back as host Fq12 ints."""
    px, py, pinf = pr.g1_affine_from_oracle([p_pt, None])
    qx, qy, qinf = pr.g2_affine_from_oracle([q_pt, None])
    mask = np.array([True, False])
    f = pr.miller_product_jit(
        jnp.asarray(px), jnp.asarray(py), jnp.asarray(pinf),
        jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(qinf),
        jnp.asarray(mask))
    return FQ12.to_int_pairs(f[None])[0]


class TestMillerFinalExp:
    """Miller loop + final exponentiation vs the host chains on known
    pairing vectors (the generator pair and a real signature pair)."""

    def test_pairing_matches_host_fast_chain(self):
        sig, pk, _h = _vote(0xBEEF, b"pairing-vector-1")
        mdev = _device_miller_one_pair(sig, pk)
        # Identical field element after final exponentiation, not just
        # a verdict: every subfield factor the twist-side device Miller
        # loop dropped is dead under the (shared cube) exponent, so the
        # host fast chain over the DEVICE Miller value must reproduce
        # the host pairing exactly.
        assert oracle.final_exponentiation(mdev) == oracle.pairing(pk, sig)

    def test_miller_agrees_under_naive_final_exp(self):
        """The §7(b) oracle cross-check the issue names: device Miller
        output → HOST final_exponentiation_naive equals the host Miller
        → naive chain (the dropped line denominators live in Fq2 and
        die under the full (p¹²−1)/r exponent)."""
        q, p = oracle.G2_GEN, oracle.G1_GEN
        mdev = _device_miller_one_pair(p, q)
        m_host = oracle.miller_loop(
            oracle.untwist(q),
            (oracle.fq_to_fq12(p[0]), oracle.fq_to_fq12(p[1])))
        assert oracle.final_exponentiation_naive(mdev) == \
            oracle.final_exponentiation_naive(m_host)


def _verdict_sets(n_sets):
    """n random (sig, pk, msg) verify-shaped pair sets, every third one
    invalid (wrong message / wrong signer / tampered signature point —
    all still valid curve points, so the pairing itself must say no)."""
    sets, want = [], []
    for i in range(n_sets):
        sk = 0x5151 + 977 * i
        sig, pk, h_pt = _vote(sk, b"multi-%d" % i)
        kind = i % 3
        if kind == 1:
            h_pt = oracle.hash_to_g1(sm3_hash(b"other-%d" % i), b"")
        elif kind == 2:
            sig = oracle.g1_mul(sig, 5)  # valid point, forged signature
        sets.append(((sig, NEG_G2), (h_pt, pk)))
        want.append(kind == 0)
    return sets, want


def _device_verdicts(sets):
    """One staged verdict call per set, through the SAME rung-2 shapes
    the production provider dispatches — every set shares the two
    cached kernels (ops/pairing.py compile-cost split)."""
    out = []
    for s in sets:
        px, py, pinf = pr.g1_affine_from_oracle([s[0][0], s[1][0]])
        qx, qy, qinf = pr.g2_affine_from_oracle([s[0][1], s[1][1]])
        v = pr.multi_pairing_is_one_staged(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(pinf),
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(qinf),
            jnp.asarray(np.ones(2, bool)))
        out.append(bool(v))
    return out


class TestMultiPairing:
    def test_verdict_identity_small(self):
        n = int(os.environ.get("PAIRING_TEST_VECTORS", "6"))
        sets, want = _verdict_sets(n)
        got = _device_verdicts(sets)
        host = [oracle.multi_pairing_is_one(list(s)) for s in sets]
        assert got == host == want

    def test_infinity_pairs_skip_like_host(self):
        sig, pk, h_pt = _vote(0xA11CE, b"inf-skip")
        # Padded to the production rung-5 shape (the multi-hash rung):
        # one infinity pair + two masked padding lanes, all must skip.
        px, py, pinf = pr.g1_affine_from_oracle([sig, h_pt, None,
                                                 None, None])
        qx, qy, qinf = pr.g2_affine_from_oracle([NEG_G2, pk, pk, pk, pk])
        mask = np.array([True, True, True, False, False])
        got = bool(pr.multi_pairing_is_one_staged(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(pinf),
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(qinf),
            jnp.asarray(mask)))
        # Host skips None pairs; the masked device lanes must too.
        assert got is oracle.multi_pairing_is_one(
            [(sig, NEG_G2), (h_pt, pk), (None, pk)])

    @pytest.mark.slow
    def test_verdict_identity_256(self):
        """The r06 acceptance sweep: ≥256 randomized valid+invalid
        vectors, device verdicts bit-identical to the host oracle, on
        the CPU lane (nightly; PAIRING_TEST_VECTORS overrides)."""
        n = int(os.environ.get("PAIRING_TEST_VECTORS", "256"))
        sets, want = _verdict_sets(n)
        got = _device_verdicts(sets)
        host = [oracle.multi_pairing_is_one(list(s)) for s in sets]
        assert got == host == want


KEYS = [0x2222 * (i + 1) + 13 for i in range(6)]


@pytest.fixture(scope="module")
def cpus():
    return [CpuBlsCrypto(k) for k in KEYS]


@pytest.fixture(scope="module")
def tpu_pairing(cpus):
    t = TpuBlsCrypto(KEYS[0], device_threshold=1, device_pairing=True)
    t.update_pubkeys([c.pub_key for c in cpus])
    return t


class TestProviderDevicePairing:
    """TpuBlsCrypto with the device-resident pairing verdicts on: exact
    agreement with the CPU provider, one shared final exponentiation
    per flush (stage-ring pinned), host oracle only on injected
    faults."""

    def test_verify_batch_exact(self, cpus, tpu_pairing):
        h = sm3_hash(b"dev-pairing-1")
        sigs = [c.sign(h) for c in cpus]
        voters = [c.pub_key for c in cpus]
        sigs[2] = cpus[2].sign(sm3_hash(b"wrong"))  # bad lane localized
        want = [c.verify_signature(s, h, v)
                for c, s, v in zip(cpus, sigs, voters)]
        got = tpu_pairing.verify_batch(sigs, [h] * len(cpus), voters)
        assert got == want == [True, True, False, True, True, True]
        assert tpu_pairing.pairing_host_fallbacks == 0

    def test_one_final_exp_per_flush(self, cpus, tpu_pairing):
        """pairing stage count == flush count, not signature count: the
        shared-final-exponentiation acceptance assert."""
        prof = DeviceProfiler()
        tpu_pairing.bind_profiler(prof)
        try:
            h = sm3_hash(b"dev-pairing-flushes")
            sigs = [c.sign(h) for c in cpus]
            voters = [c.pub_key for c in cpus]
            flushes = 3
            for _ in range(flushes):
                assert all(tpu_pairing.verify_batch(
                    sigs, [h] * len(cpus), voters))
            totals = prof.stage_totals()
            assert totals["verify_batch/pairing"]["count"] == flushes
            assert totals["verify_batch/readback"]["count"] == flushes
        finally:
            tpu_pairing.bind_profiler(None)

    def test_multi_hash_fused(self, cpus, tpu_pairing):
        h1, h2 = sm3_hash(b"mh-a"), sm3_hash(b"mh-b")
        sigs = ([c.sign(h1) for c in cpus[:3]]
                + [c.sign(h2) for c in cpus[3:]])
        hashes = [h1] * 3 + [h2] * 3
        voters = [c.pub_key for c in cpus]
        assert tpu_pairing.verify_batch(sigs, hashes, voters) == [True] * 6

    def test_verify_aggregated(self, cpus, tpu_pairing):
        h = sm3_hash(b"qc-dev-pairing")
        voters = [c.pub_key for c in cpus]
        agg = tpu_pairing.aggregate_signatures(
            [c.sign(h) for c in cpus], voters)
        assert tpu_pairing.verify_aggregated_signature(agg, h, voters)
        assert not tpu_pairing.verify_aggregated_signature(
            agg, sm3_hash(b"other"), voters)

    def test_injected_pairing_fault_host_fallback(self, cpus, monkeypatch):
        """CONC002's contract end to end: a device fault on the pairing
        dispatch feeds the breaker, lands in pairing_host_fallbacks,
        and the HOST oracle still returns exact verdicts."""
        from consensus_overlord_tpu.crypto import tpu_provider as mod
        t = TpuBlsCrypto(KEYS[0], device_threshold=1, device_pairing=True)
        t.update_pubkeys([c.pub_key for c in cpus])

        def boom(*_a):
            raise RuntimeError("injected pairing device fault")

        monkeypatch.setattr(mod._SingleChipKernels, "multi_pairing",
                            staticmethod(boom))
        h = sm3_hash(b"fault-pairing")
        sigs = [c.sign(h) for c in cpus]
        voters = [c.pub_key for c in cpus]
        sigs[4] = cpus[4].sign(sm3_hash(b"nope"))
        got = t.verify_batch(sigs, [h] * len(cpus), voters)
        assert got == [True, True, True, True, False, True]
        assert t.pairing_host_fallbacks >= 1
        assert t.breaker.status()["state"] != "open"  # one fault ≠ open
        # Degraded-state surface carries the counter for /statusz.
        assert t.degraded_status()["pairing_host_fallbacks"] >= 1


class TestG2TableMsm:
    def test_table_msm_exact(self, cpus, monkeypatch):
        """g2_table_msm promoted path: verdicts identical to the ladder
        path (tiny capacity rung so the table build stays test-sized)."""
        from consensus_overlord_tpu.crypto import tpu_provider as mod
        monkeypatch.setattr(mod, "_PK_CAPS", (8,))
        t = TpuBlsCrypto(KEYS[0], device_threshold=1, g2_table_msm=True)
        t.update_pubkeys([c.pub_key for c in cpus])
        assert t._pk_tab is not None  # rebuilt at the reconfigure point
        h = sm3_hash(b"tables-1")
        sigs = [c.sign(h) for c in cpus]
        voters = [c.pub_key for c in cpus]
        sigs[1] = cpus[1].sign(sm3_hash(b"bad"))
        got = t.verify_batch(sigs, [h] * len(cpus), voters)
        assert got == [True, False, True, True, True, True]
