"""Self-healing mesh (ISSUE 18): the dispatch watchdog, the
MeshSupervisor escalation ladder, and device-loss/DCN-stall chaos.

The acceptance surface: a wedged dispatch becomes a DispatchTimeout
breaker failure within the rung-scaled deadline + epsilon (never a hung
fleet); a lost lane is quarantined and the provider rebuilds a survivor
sub-mesh whose verdicts stay bit-identical to the host oracle; the
ladder walks back up once the fault clears; and a seeded chaos schedule
with device_loss + dcn_stall events commits with zero violations.

The standing guarantee under test at every rung: verdicts are exact —
degradation costs throughput, never correctness or liveness.
"""

import time

import pytest

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.parallel.supervisor import RUNGS, MeshSupervisor


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# MeshSupervisor ladder logic (no hardware, stub provider)
# ---------------------------------------------------------------------------

class StubProvider:
    """Duck-typed provider: records apply_mesh_rung calls."""

    def __init__(self, lanes=8, fail_rungs=()):
        self._lanes = lanes
        self.fail_rungs = set(fail_rungs)
        self.applied = []

    def mesh_device_names(self):
        return [f"sim:{i}" for i in range(self._lanes)]

    def apply_mesh_rung(self, rung, quarantined):
        if rung in self.fail_rungs:
            raise RuntimeError(f"rebuild of {rung} failed")
        self.applied.append((rung, tuple(quarantined)))


class TestSupervisorLadder:
    def _sup(self, provider=None, **kw):
        clock = FakeClock()
        kw.setdefault("step_threshold", 2)
        kw.setdefault("probe_successes", 2)
        kw.setdefault("probe_cooldown_s", 5.0)
        sup = MeshSupervisor(provider or StubProvider(), clock=clock, **kw)
        return sup, clock

    def test_rung_order(self):
        assert RUNGS == ("full_mesh", "sub_mesh", "single_chip",
                         "host_oracle")

    def test_attributed_loss_quarantines_and_rebuilds_sub_mesh(self):
        from consensus_overlord_tpu.crypto.breaker import DeviceLossError

        provider = StubProvider()
        sup, _ = self._sup(provider)
        e = DeviceLossError("sim:5")
        sup.record_failure("verify_batch", e)
        assert sup.rung == "full_mesh"  # below threshold
        sup.record_failure("verify_batch", e)
        assert sup.rung == "sub_mesh"
        assert sup.quarantined_devices() == ["sim:5"]
        assert provider.applied == [("sub_mesh", ("sim:5",))]

    def test_success_resets_the_failure_streak(self):
        from consensus_overlord_tpu.crypto.breaker import DeviceLossError

        sup, _ = self._sup()
        sup.record_failure("verify_batch", DeviceLossError("sim:1"))
        sup.record_success()
        sup.record_failure("verify_batch", DeviceLossError("sim:1"))
        assert sup.rung == "full_mesh"  # streak broken: never 2 in a row

    def test_unattributed_failure_falls_to_single_chip(self):
        provider = StubProvider()
        sup, _ = self._sup(provider)
        for _ in range(2):
            sup.record_failure("aggregate", RuntimeError("wedged"))
        assert sup.rung == "single_chip"
        assert sup.quarantined_devices() == []

    def test_straggler_attribution_names_the_lane(self):
        class Straggler:
            @staticmethod
            def flagged_devices():
                return ["sim:3"]

        provider = StubProvider()
        sup, _ = self._sup(provider, straggler=Straggler())
        for _ in range(2):
            sup.record_failure("verify_batch", RuntimeError("slow"))
        assert sup.rung == "sub_mesh"
        assert sup.quarantined_devices() == ["sim:3"]

    def test_full_down_and_up_walk(self):
        from consensus_overlord_tpu.crypto.breaker import DeviceLossError

        provider = StubProvider()
        sup, clock = self._sup(provider)

        def down(exc):
            for _ in range(2):
                sup.record_failure("verify_batch", exc)

        down(DeviceLossError("sim:5"))
        assert sup.rung == "sub_mesh"
        down(RuntimeError("wedged"))
        assert sup.rung == "single_chip"
        down(RuntimeError("wedged"))
        assert sup.rung == "host_oracle"
        down(RuntimeError("still dead"))
        assert sup.rung == "host_oracle"  # bottom rung holds

        # Probe successes inside the dwell window do NOT promote.
        sup.record_success()
        sup.record_success()
        assert sup.rung == "host_oracle"
        clock.t += 5.1
        for want in ("single_chip", "sub_mesh", "full_mesh"):
            sup.record_success()
            sup.record_success()
            assert sup.rung == want
        # The climb back through sub_mesh kept the quarantine, and the
        # final promotion probes the old lane with real traffic.
        assert sup.quarantined_devices() == []
        assert [r for r, _ in provider.applied] == [
            "sub_mesh", "single_chip", "host_oracle", "single_chip",
            "sub_mesh", "full_mesh"]
        st = sup.statusz()
        assert st["rung"] == "full_mesh"
        assert st["transitions"] == 6
        assert [t["reason"] for t in st["recent"][-3:]] == ["probe"] * 3

    def test_host_oracle_lets_one_probe_per_cooldown(self):
        sup, clock = self._sup()
        for _ in range(6):
            sup.record_failure("verify_batch", RuntimeError("dead"))
        assert sup.rung == "host_oracle"
        assert sup.allow_device()       # the single half-open probe
        assert not sup.allow_device()   # everyone else: host oracle
        clock.t += 5.1
        assert sup.allow_device()       # next probe window
        # Above the bottom rung the gate is wide open.
        sup2, _ = self._sup()
        assert sup2.allow_device() and sup2.allow_device()

    def test_failed_rebuild_degrades_further_instead_of_wedging(self):
        from consensus_overlord_tpu.crypto.breaker import DeviceLossError

        provider = StubProvider(fail_rungs={"sub_mesh"})
        sup, _ = self._sup(provider)
        for _ in range(2):
            sup.record_failure("verify_batch", DeviceLossError("sim:2"))
        assert sup.rung == "single_chip"
        assert sup.statusz()["recent"][-1]["reason"].startswith(
            "rebuild_failed")

    def test_too_few_survivors_skips_the_sub_mesh_rung(self):
        provider = StubProvider(lanes=2)
        sup, _ = self._sup(provider)
        from consensus_overlord_tpu.crypto.breaker import DeviceLossError

        for _ in range(2):
            sup.record_failure("verify_batch", DeviceLossError("sim:0"))
        assert sup.rung == "single_chip"  # 1 survivor is not a mesh

    def test_transitions_are_metered_and_recorded(self):
        from consensus_overlord_tpu.crypto.breaker import DeviceLossError
        from consensus_overlord_tpu.obs import Metrics, snapshot
        from consensus_overlord_tpu.obs.flightrec import FlightRecorder

        m = Metrics()
        rec = FlightRecorder(capacity=16)
        sup, _ = self._sup(StubProvider(), metrics=m, recorder=rec)
        for _ in range(2):
            sup.record_failure("verify_batch", DeviceLossError("sim:4"))
        scraped = snapshot(m.registry)
        assert scraped[
            "mesh_ladder_transitions_total{from=full_mesh,"
            "reason=verify_batch: DeviceLossError,to=sub_mesh}"] == 1.0
        assert scraped["mesh_quarantined_devices"] == 1.0
        kinds = [e["kind"] for e in rec.tail(16)]
        assert "ladder_transition" in kinds


# ---------------------------------------------------------------------------
# Dispatch watchdog (real provider, single chip)
# ---------------------------------------------------------------------------

N = 4
KEYS = [0x7A31 * (i + 1) + 5 for i in range(N)]


@pytest.fixture(scope="module")
def signed_batch():
    h = sm3_hash(b"watchdog-block")
    sigs = [oracle.sign(k, h) for k in KEYS]
    pks = [oracle.sk_to_pk(k) for k in KEYS]
    return h, sigs, pks


class TestDispatchWatchdog:
    def test_deadline_scales_with_the_batch_rung(self):
        from consensus_overlord_tpu.crypto.tpu_provider import (
            _PAD_SIZES,
            TpuBlsCrypto,
        )

        t = TpuBlsCrypto(0xBEEF, dispatch_deadline_s=2.0)
        assert t._deadline_for(_PAD_SIZES[0]) == 2.0
        assert t._deadline_for(4 * _PAD_SIZES[0]) == 4.0  # sqrt scaling
        assert t._deadline_for(0) == 2.0  # floor at the base
        off = TpuBlsCrypto(0xBEEF, dispatch_deadline_s=0.0)
        assert off._deadline_for(8192) is None

    @pytest.mark.slow  # real pairing kernels + host re-verify: nightly lane
    def test_wedged_dispatch_times_out_with_exact_host_verdicts(
            self, signed_batch):
        """The r18 acceptance slice on one chip: a DCN stall longer than
        the deadline surfaces as a DispatchTimeout breaker failure
        within deadline + epsilon (not a 20 s hang), the batch
        re-verifies exactly on the host oracle, and the breaker status
        names the timeout."""
        from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto

        h, sigs, pks = signed_batch
        # Warm with the watchdog off so compile time can't race the
        # deadline, then arm it for the wedged dispatch.
        t = TpuBlsCrypto(KEYS[0], device_threshold=1,
                         qc_device_threshold=10**9,
                         dispatch_deadline_s=0.0)
        t.update_pubkeys(pks)
        sigs = list(sigs)
        sigs[1] = oracle.sign(KEYS[1], sm3_hash(b"forged"))
        want = [i != 1 for i in range(N)]
        assert t.verify_batch(sigs, [h] * N, pks) == want  # warm, device

        from consensus_overlord_tpu.crypto.breaker import DispatchTimeout

        t._dispatch_deadline_s = 1.5
        t.inject_dcn_stall(30.0)
        # The watchdog primitive itself: fires at the deadline, not at
        # the end of the 30 s wedge.
        t0 = time.monotonic()
        with pytest.raises(DispatchTimeout):
            t._watched(lambda: None, size=8, path="verify_batch")
        cut = time.monotonic() - t0
        assert 1.4 <= cut < 1.5 + 1.0, \
            f"watchdog fired at {cut:.2f}s (deadline 1.5s)"
        # End to end: the wedged batch re-verifies exactly on the host
        # oracle (elapsed includes that re-verify, so the bound only
        # proves the 30 s wedge was cut short, not ridden out).
        t0 = time.monotonic()
        got = t.verify_batch(sigs, [h] * N, pks)
        elapsed = time.monotonic() - t0
        t.inject_dcn_stall(0.0)
        assert got == want                     # exact host re-verify
        assert elapsed < 15.0, \
            f"verify took {elapsed:.1f}s — rode out the wedge"
        st = t.breaker.status()
        assert "DispatchTimeout" in st["last_failure_reason"]
        assert t.pairing_host_fallbacks == 0   # batch path, not pairing

    def test_breaker_status_serves_cooldown_remaining(self):
        from consensus_overlord_tpu.crypto.breaker import CircuitBreaker

        clock = FakeClock()
        b = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=clock)
        st = b.status()
        assert st["last_failure_reason"] == ""
        assert st["cooldown_remaining_s"] == 0.0
        b.record_failure("verify_batch: DispatchTimeout")
        clock.t += 2.0
        st = b.status()
        assert st["state"] == "open"
        assert st["last_failure_reason"] == "verify_batch: DispatchTimeout"
        assert st["cooldown_remaining_s"] == pytest.approx(3.0)
        clock.t += 3.1
        assert b.allow()  # half-open probe
        b.record_success()
        st = b.status()
        assert st["cooldown_remaining_s"] == 0.0  # closed: no countdown
        assert st["last_failure_reason"] != ""    # sticky: forensics


# ---------------------------------------------------------------------------
# Ladder walk on the 8-lane virtual mesh (real provider + kernels)
# ---------------------------------------------------------------------------

class TestMeshLadderEndToEnd:
    @pytest.mark.slow  # compiles the 8- AND 7-lane mesh kernel sets and
    # host-verifies 16-sig batches at every rung (~10 min on one core):
    # the nightly slow lane's job; check.yml's pairing_smoke
    # --inject-loss covers the ladder step per push.
    def test_device_loss_walks_down_and_up_with_exact_verdicts(self):
        """The tentpole walk: lose a lane -> quarantine + 7-lane
        sub-mesh rebuild; an unattributed fault -> single chip; fault
        clears -> climb back to the full mesh.  verify_batch must match
        the host-oracle expectation bit-for-bit at EVERY rung."""
        import jax

        from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
        from consensus_overlord_tpu.parallel import make_mesh

        assert len(jax.devices()) >= 8
        provider = TpuBlsCrypto(0xD1CE, device_threshold=1,
                                qc_device_threshold=10**9,
                                mesh=make_mesh(8))
        # The long dwell parks the ladder wherever the walk-down puts
        # it; the climb phase below zeroes it to let traffic probe up.
        sup = MeshSupervisor(provider, step_threshold=1,
                             probe_successes=2, probe_cooldown_s=60.0)
        provider.attach_supervisor(sup)
        batch = 16
        h = sm3_hash(b"ladder-block")
        sks = [7000 + 13 * i for i in range(batch)]
        sigs = [oracle.sign(sk, h) for sk in sks]
        pks = [oracle.sk_to_pk(sk) for sk in sks]
        provider.update_pubkeys(pks)
        sigs[3] = oracle.sign(sks[3], sm3_hash(b"other message"))
        want = [i != 3 for i in range(batch)]

        def verify():
            return provider.verify_batch(sigs, [h] * batch, pks)

        assert verify() == want
        assert sup.rung == "full_mesh" and provider._kernels.lanes == 8

        # Rung 2: lose lane 5 — quarantined, sub-mesh rebuilt over the
        # 7 survivors, and the faulted batch still verdicts exactly
        # (host fallback for the one that died mid-flight).
        lane = provider.mesh_device_names()[5]
        provider.inject_device_loss(lane, seconds=3600.0)
        assert verify() == want
        assert sup.rung == "sub_mesh"
        assert sup.quarantined_devices() == [lane]
        assert provider._kernels.lanes == 7
        assert lane not in provider._current_lane_names()
        # The rebuilt sub-mesh dispatches clean while the lane is still
        # lost — this is the self-healing claim, not just a fallback.
        fallbacks0 = provider.breaker.total_fallbacks
        assert verify() == want
        assert provider.breaker.total_fallbacks == fallbacks0

        # Rung 3: an unattributed injected fault (no .device, no
        # straggler flag) condemns the whole mesh -> single chip.
        provider.breaker.inject_faults(0.001, min_faults=1)
        assert verify() == want
        provider.breaker.clear_injected_faults()
        assert sup.rung == "single_chip"
        assert provider._kernels.lanes == 1
        assert verify() == want  # single-chip kernels, exact verdicts

        # Fault clears: traffic probes the ladder back to the top.
        provider.inject_device_loss(lane, seconds=0.0)
        sup.probe_cooldown_s = 0.0
        for _ in range(12):
            assert verify() == want
            if sup.rung == "full_mesh":
                break
        assert sup.rung == "full_mesh"
        assert provider._kernels.lanes == 8
        assert sup.quarantined_devices() == []
        walked = [(tr["from"], tr["to"]) for tr in sup.statusz()["recent"]]
        assert ("full_mesh", "sub_mesh") in walked
        assert ("sub_mesh", "single_chip") in walked
        assert ("single_chip", "sub_mesh") in walked
        assert ("sub_mesh", "full_mesh") in walked


# ---------------------------------------------------------------------------
# Seeded device_loss / dcn_stall chaos through the real CLI
# ---------------------------------------------------------------------------

class TestMeshChaosRun:
    def test_schedule_draws_are_append_only(self):
        """The new mesh draws ride AFTER every legacy draw: seeds must
        reproduce the exact legacy schedule when mesh counts are 0, and
        adding mesh events must not perturb the legacy prefix."""
        from consensus_overlord_tpu.sim import ChaosSchedule

        legacy = ChaosSchedule.generate(7, heights=12, n_validators=4)
        mesh = ChaosSchedule.generate(7, heights=12, n_validators=4,
                                      device_losses=2, dcn_stalls=1)
        n = len(legacy.events)
        assert mesh.events[:n] == legacy.events
        extra = mesh.events[n:]
        assert sorted(e.kind for e in extra) == [
            "dcn_stall", "device_loss", "device_loss"]
        for e in extra:
            if e.kind == "device_loss":
                assert 0 <= e.device < 8
            assert e.duration_s > 0

    def test_seeded_mesh_chaos_run_exits_zero(self):
        """sim/run.py --chaos with device_loss + dcn_stall events: the
        fleet commits every height with zero safety violations, the
        supervisors walk (and re-climb) the ladder, and the summary
        carries the transition history."""
        import json
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, "-m", "consensus_overlord_tpu.sim.run",
             "--validators", "4", "--heights", "6", "--interval-ms", "40",
             "--crypto", "simhash", "--chaos", "--seed", "7",
             "--chaos-crashes", "0", "--chaos-stalls", "0",
             "--chaos-partitions", "0",
             "--chaos-device-losses", "2", "--chaos-dcn-stalls", "1",
             "--chaos-mesh-window-ms", "300", "--shared-frontier"],
            capture_output=True, text=True, timeout=300, cwd=repo,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
        summary = json.loads(out.stdout.splitlines()[-1])
        assert summary["chaos"]["safety_violations"] == 0
        # Per-event stat dicts, one per fired window, lane attributed.
        losses = summary["chaos"]["device_losses"]
        stalls = summary["chaos"]["dcn_stalls"]
        assert len(losses) == 2 and len(stalls) == 1, (losses, stalls)
        assert all(0 <= e["device"] < 8 for e in losses), losses
        assert summary["chaos"]["events_fired"] == 3
        assert "ladder" in summary
        rungs = {s["rung"] for s in summary["ladder"]["supervisors"]}
        assert rungs == {"full_mesh"}  # drained back to healthy
