"""Causal commit tracing tests (obs/causal.py + the envelope fabric).

Pins the contracts the critical-path work leans on:

* the exact-partition solve — per trace the stage seconds sum to the
  commit latency and the shares sum to 1.0, whatever events arrived
  (missing proposal receipt, missing quorum, out-of-order clocks);
* cross-node trace linking — every validator derives the same Jaeger
  trace id from the height, spans carry the node address tag;
* the envelope fabric end-to-end — traces keep flowing across a
  restart_node crash/revive cycle at 4 shards, inter-shard deliveries
  show up as via_trunk, and the tracer costs the fabric zero RNG draws
  (the golden seed-7 fixtures stay byte-identical);
* scripts/waterfall.py --critical-path reconstructs every traced
  height and exits 5 (not 4) when no commit-tagged data is present.
"""

import asyncio
import hashlib
import json
import pathlib
import subprocess
import sys

import pytest

from consensus_overlord_tpu.core.types import (AggregatedSignature,
                                               AggregatedVote, Proposal,
                                               SignedProposal, VoteType)
from consensus_overlord_tpu.obs.causal import (STAGES, CommitTracer,
                                               height_trace_id)

DATA = pathlib.Path(__file__).parent / "data"
WATERFALL = pathlib.Path(__file__).parent.parent / "scripts" / "waterfall.py"

NODE = b"\x01" * 8
PEER = b"\x02" * 8
HASH = b"\x11" * 32


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _proposal(height, round_=0, proposer=PEER):
    return SignedProposal(
        Proposal(height=height, round=round_, content=b"blk",
                 block_hash=HASH, lock=None, proposer=proposer),
        signature=b"\x00" * 96)


def _qc(height, round_=0, vote_type=VoteType.PRECOMMIT, block_hash=HASH):
    return AggregatedVote(
        signature=AggregatedSignature(b"\x00" * 96, b"\x07"),
        vote_type=vote_type, height=height, round=round_,
        block_hash=block_hash, leader=PEER)


class TestSolver:
    """The exact-partition critical-path solve."""

    def test_full_event_stream_partitions_exactly(self):
        tr = CommitTracer()
        t0 = 100.0
        tr.on_enter_height(NODE, 5, t0)
        # enq, due, drained (trunk), delivered, via_trunk
        env = (t0 + 0.001, t0 + 0.004, t0 + 0.003, t0 + 0.010, True)
        tr.on_recv(NODE, _proposal(5), t0 + 0.010, env)
        tr.on_quorum(NODE, VoteType.PRECOMMIT, 5, 0, t0 + 0.030, votes=3)
        tr.on_aggregate(NODE, 5, 0.002)
        tr.on_qc_verify(NODE, 5, 0.003)
        tr.on_wal_save(NODE, 5, 0.004)
        tr.on_commit(NODE, 5, t0 + 0.050)
        assert len(tr.completed) == 1
        t = tr.completed[0]
        assert t.height == 5 and t.node == NODE.hex()
        assert t.via_trunk and t.quorum_votes == 3
        assert t.total_s == pytest.approx(0.050)
        # Exact partition: stage seconds sum to the latency, shares to 1.
        assert sum(t.stages.values()) == pytest.approx(t.total_s)
        assert sum(t.shares.values()) == pytest.approx(1.0)
        assert set(t.stages) == set(STAGES)
        # Head split: trunk = drained-enq, queue = delivered-due,
        # propagation is the remainder of [enter, prop_recv].
        assert t.stages["trunk_hop"] == pytest.approx(0.002)
        assert t.stages["router_queue_wait"] == pytest.approx(0.006)
        assert t.stages["proposal_propagation"] == pytest.approx(0.002)
        assert t.stages["quorum_tail"] == pytest.approx(0.020)
        assert t.stages["qc_verify"] == pytest.approx(0.005)
        assert t.stages["wal_fsync"] == pytest.approx(0.004)
        assert t.stages["commit"] == pytest.approx(0.011)

    def test_missing_events_fall_back_to_commit_stage(self):
        """A trace with only enter + commit (no proposal receipt, no
        quorum crossing) still partitions: everything lands in the
        commit stage and the shares still sum to 1.0."""
        tr = CommitTracer()
        tr.on_enter_height(NODE, 1, 10.0)
        tr.on_commit(NODE, 1, 10.5)
        t = tr.completed[0]
        assert sum(t.shares.values()) == pytest.approx(1.0)
        assert t.stages["commit"] == pytest.approx(0.5)

    def test_zero_total_assigns_commit_share(self):
        tr = CommitTracer()
        tr.on_enter_height(NODE, 1, 10.0)
        tr.on_commit(NODE, 1, 10.0)
        t = tr.completed[0]
        assert t.shares["commit"] == 1.0
        assert sum(t.shares.values()) == pytest.approx(1.0)

    def test_out_of_order_clocks_clamp_nonnegative(self):
        """Proposal receipt stamped after commit and a quorum stamped
        before the proposal must clamp monotone: no negative stages,
        shares still a partition."""
        tr = CommitTracer()
        tr.on_enter_height(NODE, 2, 50.0)
        tr.on_recv(NODE, _proposal(2), 51.0, None)   # after commit below
        tr.on_quorum(NODE, VoteType.PRECOMMIT, 2, 0, 50.1, votes=3)
        tr.on_commit(NODE, 2, 50.4)
        t = tr.completed[0]
        assert all(v >= 0.0 for v in t.stages.values()), t.stages
        assert sum(t.stages.values()) == pytest.approx(t.total_s)
        assert sum(t.shares.values()) == pytest.approx(1.0)

    def test_measured_crypto_and_wal_clamp_to_tail(self):
        """agg/qc-verify/WAL seconds larger than the post-quorum tail
        (overlapped work) are clamped so the partition stays exact."""
        tr = CommitTracer()
        tr.on_enter_height(NODE, 3, 0.0)
        tr.on_recv(NODE, _proposal(3), 0.010, None)
        tr.on_quorum(NODE, VoteType.PRECOMMIT, 3, 0, 0.020, votes=3)
        tr.on_qc_verify(NODE, 3, 1.0)    # way past the 10 ms tail
        tr.on_wal_save(NODE, 3, 1.0)
        tr.on_commit(NODE, 3, 0.030)
        t = tr.completed[0]
        assert t.stages["qc_verify"] == pytest.approx(0.010)
        assert t.stages["wal_fsync"] == pytest.approx(0.0)
        assert t.stages["commit"] == pytest.approx(0.0)
        assert sum(t.shares.values()) == pytest.approx(1.0)

    def test_nonleader_qc_receipt_ends_quorum_tail(self):
        """A non-leader has no on_quorum crossing: the precommit QC's
        arrival (AggregatedVote via on_recv) ends the quorum tail.
        Prevote QCs and nil QCs must not."""
        tr = CommitTracer()
        tr.on_enter_height(NODE, 7, 0.0)
        tr.on_recv(NODE, _proposal(7), 0.010, None)
        tr.on_recv(NODE, _qc(7, vote_type=VoteType.PREVOTE), 0.015, None)
        tr.on_recv(NODE, _qc(7, block_hash=b""), 0.018, None)
        assert tr._pending[(NODE, 7)].t_quorum is None
        tr.on_recv(NODE, _qc(7), 0.020, None)
        assert tr._pending[(NODE, 7)].t_quorum == 0.020
        tr.on_commit(NODE, 7, 0.030)
        assert tr.completed[0].stages["quorum_tail"] == pytest.approx(0.010)

    def test_first_quorum_stamp_wins(self):
        """The leader's own (2f+1)-th-vote crossing precedes any QC
        echo; a later receipt must not move the stamp."""
        tr = CommitTracer()
        tr.on_enter_height(NODE, 4, 0.0)
        tr.on_quorum(NODE, VoteType.PRECOMMIT, 4, 0, 0.010, votes=3)
        tr.on_recv(NODE, _qc(4), 0.025, None)
        assert tr._pending[(NODE, 4)].t_quorum == 0.010

    def test_height_settled_finalizes_once(self):
        """Followers finalize at the status push (path="status"); a
        node whose on_commit already fired ignores the later settle —
        first pop wins, no double-count."""
        tr = CommitTracer()
        tr.on_enter_height(NODE, 6, 0.0)
        tr.on_height_settled(NODE, 6, 0.5)
        assert tr.completed[0].path == "status"
        tr.on_enter_height(PEER, 6, 0.0)
        tr.on_commit(PEER, 6, 0.3)
        tr.on_height_settled(PEER, 6, 0.5)
        assert len(tr.completed) == 2
        assert tr.completed[1].path == "commit"
        assert tr.completed[1].total_s == pytest.approx(0.3)

    def test_verify_round_ids_join_the_profile_ring(self):
        """The frontier's aggregate-path round ids recorded during the
        interval ride the trace as verify_round_ids — the join key into
        the device-profile ring."""
        tr = CommitTracer()
        tr.on_enter_height(NODE, 8, 0.0)
        tr.on_aggregate(NODE, 8, 0.001, round_id=41)
        tr.on_qc_verify(NODE, 8, 0.002, round_id=42)
        tr.on_qc_verify(NODE, 8, 0.001)  # host path: no ring to join
        tr.on_commit(NODE, 8, 0.050)
        t = tr.completed[0]
        assert t.verify_round_ids == (41, 42)
        assert t.as_dict()["verify_round_ids"] == [41, 42]

    def test_frontier_aggregate_paths_are_round_tagged(self):
        """crypto/tenancy.py round-tags verify_aggregated/aggregate like
        every flush and exposes the id (last_agg_round_id), so the
        engine can link the trace's qc_verify stage."""
        from consensus_overlord_tpu.crypto.frontier import BatchingVerifier
        from consensus_overlord_tpu.crypto.provider import sim_crypto
        from consensus_overlord_tpu.obs.fleet import current_round_id

        async def main():
            crypto = sim_crypto(b"\x01" * 32)
            seen = []
            orig = crypto.verify_aggregated_signature

            def spy(sig, h, voters):
                seen.append(current_round_id())
                return orig(sig, h, voters)

            crypto.verify_aggregated_signature = spy
            fr = BatchingVerifier(crypto, max_batch=4)
            try:
                assert fr.last_agg_round_id is None
                await fr.verify_aggregated(b"\x00" * 96, b"\x11" * 32,
                                           [crypto.pub_key])
                assert fr.last_agg_round_id is not None
                # The dispatch thread ran under that same round tag.
                assert seen == [fr.last_agg_round_id]
            finally:
                fr.close()
        run(main())

    def test_stale_pending_traces_pruned(self):
        """A node that resynced past a height never commits it; its
        open trace must not leak (soak-safe memory)."""
        tr = CommitTracer()
        tr.on_enter_height(NODE, 1, 0.0)
        tr.on_enter_height(NODE, 2, 1.0)
        tr.on_enter_height(NODE, 10, 2.0)
        keys = [h for (n, h) in tr._pending if n == NODE]
        assert keys == [10]


class TestTraceId:
    def test_deterministic_and_height_keyed(self):
        assert height_trace_id(42) == height_trace_id(42)
        assert height_trace_id(42) != height_trace_id(43)
        assert 0 < height_trace_id(1) < (1 << 128)


class TestAggregates:
    def _commit(self, tr, height, total, t0=0.0):
        tr.on_enter_height(NODE, height, t0)
        tr.on_commit(NODE, height, t0 + total)

    def test_summary_shape_and_quantiles(self):
        tr = CommitTracer()
        for i, total in enumerate([0.010, 0.020, 0.030, 0.040]):
            self._commit(tr, i + 1, total)
        s = tr.summary()
        assert s["commits"] == 4 and s["open"] == 0
        assert s["last_height"] == 4
        assert s["p50_ms"] == pytest.approx(30.0)
        assert s["p99_ms"] == pytest.approx(40.0)
        assert set(s["stage_shares"]) == set(STAGES)
        assert sum(s["stage_shares"].values()) == pytest.approx(1.0, abs=1e-4)
        # statusz is the same document (the /statusz "commits" section).
        assert tr.statusz() == s

    def test_drift_ratio_gates_like_rss(self):
        tr = CommitTracer()
        assert tr.drift_ratio() is None
        for i in range(8):
            self._commit(tr, i, 0.010)
        assert tr.drift_ratio(min_samples=8) is None  # halves too small
        for i in range(8, 16):
            self._commit(tr, i, 0.030)
        ratio = tr.drift_ratio(min_samples=8)
        assert ratio == pytest.approx(3.0, rel=0.01)


class _CollectExporter:
    def __init__(self):
        self.spans = []

    def report(self, span):
        self.spans.append(span)


class TestExports:
    def _trace_one(self, tr, node, height, t0):
        tr.on_enter_height(node, height, t0)
        tr.on_recv(node, _proposal(height), t0 + 0.010,
                   (t0 + 0.001, t0 + 0.004, t0 + 0.003, t0 + 0.010, True))
        tr.on_quorum(node, VoteType.PRECOMMIT, height, 0, t0 + 0.030, 3)
        tr.on_commit(node, height, t0 + 0.050)

    def test_jaeger_spans_join_one_cross_node_trace(self):
        """Two validators committing the same height export spans under
        ONE height-derived trace id, each tagged with its node address —
        the cross-node trace-context propagation contract."""
        exp = _CollectExporter()
        tr = CommitTracer(exporter=exp)
        self._trace_one(tr, NODE, 9, 100.0)
        self._trace_one(tr, PEER, 9, 100.0)
        # 1 root + len(STAGES) children per node.
        assert len(exp.spans) == 2 * (1 + len(STAGES))
        tids = {s.trace_id for s in exp.spans}
        assert tids == {height_trace_id(9)}
        nodes = {s.tags["node"] for s in exp.spans}
        assert nodes == {NODE.hex(), PEER.hex()}
        roots = [s for s in exp.spans if s.operation == "commit.height"]
        assert len(roots) == 2
        root_ids = {s.span_id for s in roots}
        for s in exp.spans:
            if s.operation != "commit.height":
                assert s.operation.startswith("commit.")
                assert s.parent_span_id in root_ids
                assert s.tags["stage"] in STAGES

    def test_perfetto_doc_loads_and_carries_critpath(self):
        tr = CommitTracer()
        self._trace_one(tr, NODE, 1, 10.0)
        self._trace_one(tr, PEER, 2, 10.1)
        doc = json.loads(json.dumps(tr.to_perfetto()))
        evs = doc["traceEvents"]
        assert any(e["ph"] == "X" and e["cat"] == "commit" for e in evs)
        assert any(e["ph"] == "X" and e["cat"] == "critpath" for e in evs)
        assert any(e["ph"] == "M" for e in evs)  # process names
        traces = doc["critpath"]["traces"]
        assert len(traces) == 2
        for t in traces:
            assert sum(t["shares"].values()) == pytest.approx(1.0)
        assert doc["critpath"]["summary"]["commits"] == 2


class TestWaterfallCritpath:
    """scripts/waterfall.py --critical-path (satellite: per-height stage
    bars, critical stage highlighted, --json, exit 5 on no data)."""

    def _dump(self, tmp_path, tracer):
        path = tmp_path / "cp.json"
        path.write_text(json.dumps(tracer.to_perfetto()))
        return path

    def _tracer_with_commits(self):
        tr = CommitTracer()
        for h in (1, 2, 3):
            tr.on_enter_height(NODE, h, float(h))
            tr.on_recv(NODE, _proposal(h), h + 0.010,
                       (h + 0.001, h + 0.004, h + 0.003, h + 0.010, True))
            tr.on_quorum(NODE, VoteType.PRECOMMIT, h, 0, h + 0.030, 3)
            tr.on_commit(NODE, h, h + 0.050)
        return tr

    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, str(WATERFALL), *argv],
            capture_output=True, text=True, timeout=60)

    def test_reconstructs_every_traced_height(self, tmp_path):
        path = self._dump(tmp_path, self._tracer_with_commits())
        text = self._run("--critical-path", str(path))
        assert text.returncode == 0, text.stderr
        assert "height 1" in text.stdout and "*" in text.stdout
        js = self._run("--critical-path", str(path), "--json")
        assert js.returncode == 0, js.stderr
        doc = json.loads(js.stdout)
        assert doc["count"] == 3 and doc["traces"] == 3
        assert [h["height"] for h in doc["heights"]] == [1, 2, 3]
        for h in doc["heights"]:
            for t in h["traces"]:
                crit = [s for s in t["segments"] if s["critical"]]
                assert len(crit) == 1  # exactly one dominant stage
                assert t["via_trunk"] is True
                starts = [s["start_s"] for s in t["segments"]]
                assert starts == sorted(starts)

    def test_exit_5_on_no_commit_tagged_data(self, tmp_path):
        path = tmp_path / "empty.json"
        path.write_text(json.dumps({"traceEvents": [],
                                    "critpath": {"traces": []}}))
        r = self._run("--critical-path", str(path))
        assert r.returncode == 5
        assert "no commit-tagged data" in r.stderr
        # Distinct from the round mode's exit 4.
        r4 = self._run(str(path))
        assert r4.returncode == 4


class TestFabricEndToEnd:
    """The envelope fabric wired through a live fleet."""

    def test_golden_fixtures_byte_identical(self):
        """The tracer costs the fabric zero RNG draws: the seed-7 golden
        fixtures pinned by the sharded-fabric and chaos suites must stay
        byte-for-byte what they were before the envelope threading."""
        pins = {
            "router_golden_seed7.json":
                "58e89ace54155c3bff30bf1f67bb9a7b"
                "91a2f2febe13b904b2367b1459db78e7",
            "chaos_schedule_seed7.json":
                "77994828ae332ee18d1f27a4dea43aa5"
                "b058ad2e33c4139313fc44355d769261",
        }
        for name, want in pins.items():
            got = hashlib.sha256((DATA / name).read_bytes()).hexdigest()
            assert got == want, f"{name} changed: {got}"

    def test_traces_cross_trunk_and_survive_restart(self):
        """8 validators on a 4-shard fabric: commit traces must flow,
        inter-shard proposals must show via_trunk provenance, and the
        revived node's traces must keep arriving after restart_node —
        trace-context propagation survives the crash/revive cycle."""
        from consensus_overlord_tpu.sim import SimNetwork

        async def main():
            tracer = CommitTracer()
            net = SimNetwork(n_validators=8, block_interval_ms=50,
                             seed=7, shards=4, causal=tracer)
            net.start(init_height=1)
            await net.run_until_height(3)
            victim = net.nodes[2]
            await victim.stop()
            await net.run_until_height(net.controller.latest_height + 2)
            revived = net.restart_node(2)
            revived.start(net.controller.latest_height + 1,
                          net.controller.block_interval_ms,
                          net.controller.authority_list())
            restart_floor = net.controller.latest_height
            await net.run_until_height(restart_floor + 3, timeout=30)
            await asyncio.sleep(0.3)
            await net.stop()

            traces = list(tracer.completed)
            assert traces, "no commit traces assembled"
            for t in traces:
                assert sum(t.shares.values()) == pytest.approx(1.0)
                assert sum(t.stages.values()) == pytest.approx(t.total_s)
            # Both settle paths show up: the relayer's own adapter
            # commit and the status-push follower traces.
            assert {t.path for t in traces} == {"commit", "status"}
            # 4 shards: proposals reaching off-shard validators carry
            # trunk provenance (the leader's own trace never does).
            assert any(t.via_trunk for t in traces)
            assert net.router.stats()["trunk_msgs"] > 0
            # The revived engine kept reporting into the shared tracer.
            revived_heights = [t.height for t in traces
                               if t.node == revived.name.hex()]
            assert revived_heights
            assert max(revived_heights) > restart_floor
            s = tracer.summary()
            assert s["commits"] == len(traces)
            assert s["p50_ms"] > 0
        run(main())
