"""scripts/bench_round.py smoke execution (ISSUE 3 satellite): the
round-latency instrument shipped twice with zero recorded runs — this
keeps it from rotting by actually executing it, CPU-lane, at N=4.

CONSENSUS_BENCH_CPU pins the JAX platform to CPU inside the script (the
axon plugin would otherwise claim the device), and the small PAD/PK_CAP
floors keep the kernel shapes tiny — the whole run is a few seconds."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "bench_round.py")


def test_bench_round_executes_at_n4():
    env = dict(os.environ)
    env.update({
        "CONSENSUS_BENCH_CPU": "1",
        "JAX_PLATFORMS": "cpu",
        "CONSENSUS_PAD_MIN": "8",
        "CONSENSUS_PK_CAP_MIN": "256",
    })
    proc = subprocess.run(
        [sys.executable, SCRIPT, "4", "1"], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"bench_round.py failed\nstdout:\n{proc.stdout[-2000:]}\n"
        f"stderr:\n{proc.stderr[-2000:]}")
    # One JSON summary line per scale, with the ledger's key fields.
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, proc.stdout[-2000:]
    summary = json.loads(lines[0])
    assert summary["metric"] == "consensus_round_p50_ms"
    assert summary["validators"] == 4
    assert summary["leader_p50_ms"] > 0
    assert summary["follower_qc_verify_p50_ms"] > 0
    assert summary["frontier_batches_per_round"] >= 1
    # The registry scrape rides along (batch-shape drift detection).
    assert summary["metrics"]["frontier_batch_size_count"] >= 1
