"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh: real TPU
hardware in the dev loop is a single chip behind a high-latency relay, so
tests force the CPU platform with 8 host devices (see task spec / SURVEY.md
§7 build order step 6).

The TPU relay registers its PJRT plugin from a sitecustomize hook at
interpreter startup and sets JAX_PLATFORMS for the whole environment, so
the env-var route is already lost by the time pytest imports this file.
JAX backends initialize lazily, though — overriding the platform through
jax.config before the first backend use reliably pins tests to CPU.
"""

import os
import sys

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (after XLA_FLAGS so the CPU backend sees it)

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The crypto kernels are big graphs (multi-hundred-iteration scans of
# field ops); persistent compilation caching makes re-runs cheap.  Must
# go through enable() — it owns the cache layout (host-fingerprinted
# namespaces); a second hand-rolled config here would write entries at
# the flat root, where enable()'s legacy prune deletes them.
from consensus_overlord_tpu.compile_cache import enable as _enable  # noqa: E402

_enable()
