"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh: real TPU
hardware in the dev loop is a single chip, so tests force the CPU platform
with 8 host devices before JAX initializes (see task spec / SURVEY.md §7
build order step 6).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
