"""Live Byzantine adversary fleet tests (sim/adversary.py + the chaos
`byzantine` / `device_fault` events).

tests/test_byzantine.py proves single forged messages injected at the
engine boundary never move the state machine; here a real Engine runs
with doctored networking — the compromised-validator threat model —
inside an n=4 / f=1 honest fleet, and every behavior must lose on
safety, keep losing on liveness, AND be visibly counted
(consensus_byzantine_rejections_total{reason}).  One combined schedule
runs crash + partition + equivocator + device_fault in a single seeded
run — the full ROADMAP resilience item."""

import asyncio

import pytest

from consensus_overlord_tpu.crypto.breaker import (
    CircuitBreaker,
    InjectedDeviceFault,
)
from consensus_overlord_tpu.crypto.provider import (
    SimDeviceCrypto,
    SimHashCrypto,
)
from consensus_overlord_tpu.obs import Metrics, snapshot
from consensus_overlord_tpu.sim import (
    BEHAVIORS,
    REJECTION_REASONS,
    ChaosRunner,
    ChaosSchedule,
    SimNetwork,
)


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def dataclasses_replace_free(event) -> dict:
    """A ChaosEvent as a dict with the height stripped — what shift()
    must leave untouched."""
    import dataclasses

    d = dataclasses.asdict(event)
    d.pop("at_height")
    return d


def rejections(metrics) -> dict:
    return {k.split("reason=", 1)[1].rstrip("}"): v
            for k, v in snapshot(metrics.registry).items()
            if k.startswith("consensus_byzantine_rejections_total{")}


def make_net(metrics, **kw):
    kw.setdefault("n_validators", 4)
    kw.setdefault("block_interval_ms", 60)
    kw.setdefault("crypto_factory",
                  lambda i: SimHashCrypto(bytes([i + 1]) * 32))
    kw.setdefault("flight_recorder_capacity", 128)
    return SimNetwork(metrics=metrics, **kw)


async def leader_index(net, height: int) -> int:
    """Index of the validator leading round 0 of `height`."""
    await asyncio.sleep(0.05)  # let engines ingest the authority list
    addr = net.nodes[0].engine.leader(height, 0)
    return next(i for i, n in enumerate(net.nodes) if n.name == addr)


# ---------------------------------------------------------------------------
# Per-behavior: safety + liveness + rejection counters, n=4 / f=1
# ---------------------------------------------------------------------------

class TestBehaviors:
    def test_equivocator_detected_and_harmless(self):
        async def main():
            m = Metrics()
            net = make_net(m, seed=3)
            net.start(init_height=1)
            idx = await leader_index(net, 3)
            net.set_behavior(idx, "equivocator")
            await net.run_until_height(6, timeout=60)
            await net.stop()
            assert not net.controller.violations
            rej = rejections(m)
            # every honest node saw both proposals and counted the pair
            assert rej.get("equivocation", 0) >= 1, rej
        run(main())

    def test_forger_artifacts_all_rejected(self):
        async def main():
            m = Metrics()
            net = make_net(m, seed=5)
            net.start(init_height=1)
            await asyncio.sleep(0.05)
            net.set_behavior(1, "forger")
            await net.run_until_height(5, timeout=60)
            await net.stop()
            assert not net.controller.violations
            rej = rejections(m)
            for reason in REJECTION_REASONS["forger"]:
                assert rej.get(reason, 0) >= 1, (reason, rej)
            # forged precommit QCs never committed anything: the chain
            # only holds controller-made blocks
            for h, content in net.controller.chain.items():
                assert content == net.controller.make_content(h)
        run(main())

    def test_replayer_duplicates_counted(self):
        async def main():
            m = Metrics()
            net = make_net(m, seed=9)
            net.start(init_height=1)
            await asyncio.sleep(0.05)
            net.set_behavior(2, "replayer")
            await net.run_until_height(5, timeout=60)
            await net.stop()
            assert not net.controller.violations
            rej = rejections(m)
            assert rej.get("replay", 0) >= 1, rej
        run(main())

    def test_withholder_forces_view_change_liveness(self):
        async def main():
            m = Metrics()
            net = make_net(m, seed=11)
            net.start(init_height=1)
            idx = await leader_index(net, 3)
            net.set_behavior(idx, "withholder")
            # The fleet must choke through the withheld round and keep
            # committing — liveness under silence is the whole test.
            await net.run_until_height(6, timeout=60)
            await net.stop()
            assert not net.controller.violations
            s = snapshot(m.registry)
            vc = sum(v for k, v in s.items()
                     if k.startswith("consensus_view_changes_total"))
            assert vc >= 1 or s.get("consensus_chokes_sent_total", 0) >= 1
        run(main())


# ---------------------------------------------------------------------------
# Adaptive adversary: tactic switching on observed engine state
# ---------------------------------------------------------------------------

class TestAdaptive:
    def test_adaptive_switches_tactics_and_stays_harmless(self):
        """Armed on a node about to lead, the adaptive behavior must
        actually ADAPT (withhold around its leader turns, fall back to
        honest otherwise — at least one recorded switch), while the
        fleet holds safety and liveness."""
        async def main():
            m = Metrics()
            net = make_net(m, seed=21)
            net.start(init_height=1)
            idx = await leader_index(net, 3)
            net.set_behavior(idx, "adaptive")
            await net.run_until_height(7, timeout=60)
            net.set_behavior(idx, None)
            await net.run_until_height(8, timeout=60)
            await net.stop()
            assert not net.controller.violations
            stats = net.nodes[idx].adversary.behavior_stats
            assert stats.get("adaptive_switch", 0) >= 1, stats
            # the leader-turn tactic must have engaged at least once
            assert (stats.get("adaptive_withhold", 0)
                    + stats.get("adaptive_equivocate", 0)) >= 1, stats
        run(main())

    def test_adaptive_replays_during_view_change_storms(self):
        """Seed the shim's observed view-change window directly: a
        non-leader node under a storm must pick the replay tactic."""
        async def main():
            m = Metrics()
            net = make_net(m, seed=23)
            net.start(init_height=1)
            await net.run_until_height(2, timeout=30)
            # a node not leading the next height: leader tactics stay
            # off at arm time, so the storm signal picks replay (the
            # rotation will hand it a turn eventually — by then the
            # replay tactic has already recorded).
            lead = await leader_index(net, 4)
            idx = next(i for i in range(len(net.nodes)) if i != lead)
            shim = net.nodes[idx].adversary
            h = net.nodes[idx].engine.height
            for r in range(3):  # a storm: 3 recent view changes
                shim.observed_view_changes.append((h, r, "choke_quorum"))
            net.set_behavior(idx, "adaptive")
            await net.run_until_height(5, timeout=60)
            await net.stop()
            assert not net.controller.violations
            stats = shim.behavior_stats
            assert stats.get("adaptive_replay", 0) >= 1, stats
        run(main())

    def test_adaptive_chaos_event_kind(self):
        """`adaptive` rides the chaos timeline as its own event kind:
        fire-time target resolution, the byzantine budget slot, and a
        disarm at window end — with tactic switches recorded."""
        async def main():
            m = Metrics()
            net = make_net(m, seed=25)
            net.start(init_height=1)
            heights = 8
            schedule = ChaosSchedule.generate(
                25, heights=heights, n_validators=4, crashes=0, stalls=0,
                partitions=0, byzantine=0, device_faults=0, adaptive=1)
            chaos = ChaosRunner(net, schedule)
            for h in range(1, heights + 1):
                await net.run_until_height(h, timeout=30)
            cap = net.controller.latest_height + 20
            while ((chaos.pending_count or chaos.byzantine_armed)
                   and net.controller.latest_height < cap):
                await net.run_until_height(
                    net.controller.latest_height + 1, timeout=30)
            await chaos.drain()
            await net.stop()
            assert not net.controller.violations
            summary = chaos.summary()
            assert summary["behaviors_active"] == ["adaptive"], summary
            switches = sum(
                n.adversary.behavior_stats.get("adaptive_switch", 0)
                for n in net.nodes)
            assert switches >= 1
            # every adversary window closed with a frontier mark pair
            for mark in summary["frontier_marks"]:
                assert mark["batches_at_disarm"] is not None
        run(main(), timeout=180)


# ---------------------------------------------------------------------------
# Chaos-schedule integration
# ---------------------------------------------------------------------------

class TestByzantineChaos:
    def test_schedule_generation_deterministic_with_byzantine(self):
        kw = dict(heights=14, n_validators=4, crashes=1, stalls=0,
                  partitions=1, byzantine=2, device_faults=1)
        a = ChaosSchedule.generate(7, **kw)
        b = ChaosSchedule.generate(7, **kw)
        c = ChaosSchedule.generate(8, **kw)
        assert a.events == b.events and a.events != c.events
        kinds = sorted(e.kind for e in a.events)
        assert kinds == ["byzantine", "byzantine", "crash",
                         "device_fault", "partition"]
        byz = [e for e in a.events if e.kind == "byzantine"]
        # round-robin through the rejection-producing behaviors first,
        # targets resolved at fire time (node=-1)
        assert sorted(e.behavior for e in byz) == sorted(BEHAVIORS[:2])
        assert all(e.node == -1 and e.heights >= 2 for e in byz)

    def test_byzantine_zero_keeps_legacy_schedules_stable(self):
        """Seeds must not shift under the grown generator: byzantine=0 /
        device_faults=0 draws the exact pre-Byzantine schedule."""
        a = ChaosSchedule.generate(7, heights=12, n_validators=4)
        kinds = sorted(e.kind for e in a.events)
        assert kinds == ["crash", "crash", "partition", "stall"]

    def test_seed7_schedule_matches_golden_fixture(self):
        """The pinned seed-7 schedule (tests/data/) must replay
        byte-for-byte: any generator change that shifts legacy event
        timing breaks every recorded chaos seed across PRs."""
        import dataclasses
        import json
        import os

        path = os.path.join(os.path.dirname(__file__), "data",
                            "chaos_schedule_seed7.json")
        with open(path) as f:
            golden = json.load(f)
        sched = ChaosSchedule.generate(
            golden["seed"], heights=golden["heights"],
            n_validators=golden["n_validators"],
            crashes=golden["crashes"], stalls=golden["stalls"],
            partitions=golden["partitions"],
            byzantine=golden["byzantine"],
            device_faults=golden["device_faults"])
        assert [dataclasses.asdict(e) for e in sched.events] \
            == golden["events"]

    def test_new_kinds_never_perturb_legacy_event_timing(self):
        """The append-only RNG draw-order contract, strengthened for
        the new kinds: a schedule that ADDS adaptive/tenant_* events
        keeps every legacy event at its exact legacy height/target —
        the new draws all happen after the legacy ones."""
        kw = dict(heights=14, n_validators=4, crashes=2, stalls=1,
                  partitions=1, byzantine=2, device_faults=1)
        legacy = ChaosSchedule.generate(7, **kw).events
        grown = ChaosSchedule.generate(
            7, **kw, adaptive=2, tenant_floods=1, tenant_stalls=1).events
        assert grown[:len(legacy)] == legacy
        extras = grown[len(legacy):]
        assert [e.kind for e in extras] == [
            "adaptive", "adaptive", "tenant_flood", "tenant_stall"]
        assert all(e.behavior == "adaptive" for e in extras[:2])
        assert all(2 <= e.at_height <= 13 for e in extras)
        # determinism of the appended draws themselves
        again = ChaosSchedule.generate(
            7, **kw, adaptive=2, tenant_floods=1, tenant_stalls=1).events
        assert again == grown

    def test_schedule_shift_displaces_heights_only(self):
        sched = ChaosSchedule.generate(7, heights=12, n_validators=4,
                                       adaptive=1)
        shifted = sched.shift(100)
        assert [e.at_height - 100 for e in shifted.events] \
            == [e.at_height for e in sched.events]
        assert [dataclasses_replace_free(e) for e in shifted.events] \
            == [dataclasses_replace_free(e) for e in sched.events]

    def test_combined_crash_partition_equivocator_device_fault(self):
        """The ROADMAP item in one seeded run: a crash-restart, a
        partition flip, a live equivocating leader, and a device fault
        driving the breaker through open -> half-open -> closed — zero
        safety violations, target height reached, adversary counted."""
        async def main():
            m = Metrics()
            net = make_net(m, seed=7, sim_device_crypto=True)
            net.start(init_height=1)
            heights = 10
            schedule = ChaosSchedule.generate(
                7, heights=heights, n_validators=4, crashes=1, stalls=0,
                partitions=1, byzantine=1, device_faults=1,
                behaviors=["equivocator"], downtime_s=0.15,
                window_s=0.15)
            chaos = ChaosRunner(net, schedule)
            try:
                for h in range(1, heights + 1):
                    await net.run_until_height(h, timeout=30)
                # schedule runway: f-bound deferrals / late windows
                cap = net.controller.latest_height + 20
                while ((chaos.pending_count or chaos.byzantine_armed)
                       and net.controller.latest_height < cap):
                    await net.run_until_height(
                        net.controller.latest_height + 1, timeout=30)
                await chaos.drain()
            except Exception:
                print(net.dump_flight_recorders(48))
                raise
            await net.stop()
            assert not net.controller.violations
            assert net.controller.latest_height >= heights
            assert chaos.summary()["events_fired"] == 4
            rej = rejections(m)
            assert rej.get("equivocation", 0) >= 1, rej
            s = snapshot(m.registry)
            for to in ("open", "half_open", "closed"):
                key = f"crypto_breaker_transitions_total{{to={to}}}"
                assert s.get(key, 0) >= 1, (to, s)
        run(main(), timeout=180)

    def test_f_bound_never_exceeded(self):
        """Two byzantine windows + a crash racing for one f=1 slot:
        the runner defers, and at no sampled instant are two nodes
        simultaneously faulty (crashed or armed)."""
        async def main():
            m = Metrics()
            net = make_net(m, seed=13)
            net.start(init_height=1)
            heights = 8
            schedule = ChaosSchedule.generate(
                13, heights=heights, n_validators=4, crashes=1, stalls=0,
                partitions=0, byzantine=2, device_faults=0,
                behaviors=["forger", "replayer"], byz_window=2,
                downtime_s=0.15)
            chaos = ChaosRunner(net, schedule)
            max_faulty = 0

            async def watch():
                nonlocal max_faulty
                while True:
                    armed = sum(1 for n in net.nodes
                                if n.adversary.active is not None)
                    crashed = sum(1 for n in net.nodes
                                  if n._task is None or n._task.done())
                    max_faulty = max(max_faulty, armed + crashed)
                    await asyncio.sleep(0.01)

            watcher = asyncio.get_running_loop().create_task(watch())
            try:
                for h in range(1, heights + 1):
                    await net.run_until_height(h, timeout=30)
                cap = net.controller.latest_height + 20
                while ((chaos.pending_count or chaos.byzantine_armed)
                       and net.controller.latest_height < cap):
                    await net.run_until_height(
                        net.controller.latest_height + 1, timeout=30)
                await chaos.drain()
            finally:
                watcher.cancel()
            await net.stop()
            assert not net.controller.violations
            assert max_faulty <= chaos.f, max_faulty
            # deferral is allowed; losing events entirely is not
            assert chaos.summary()["events_fired"] == 3
        run(main(), timeout=180)


# ---------------------------------------------------------------------------
# Device fault injection plumbing
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class TestDeviceFaultInjection:
    def test_breaker_injection_window(self):
        clock = FakeClock()
        b = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                           clock=clock)
        b.raise_if_injected()  # unarmed: no-op
        b.inject_faults(5.0)
        assert b.fault_injected
        with pytest.raises(InjectedDeviceFault):
            b.raise_if_injected("verify_batch")
        clock.t += 5.1
        b.raise_if_injected()  # window expired
        assert not b.fault_injected
        assert b.status()["total_injected"] == 1

    def test_breaker_injection_min_faults_outlasts_window(self):
        """A target that sleeps through the wall-clock window (e.g. it
        was crashed mid-schedule) must still trip the breaker:
        min_faults keeps the window armed until enough faults actually
        landed, so the chaos open->closed obligation is schedule-proof."""
        clock = FakeClock()
        b = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                           clock=clock)
        b.inject_faults(0.5, min_faults=2)
        clock.t += 5.0  # window long expired; node made no calls
        assert b.fault_injected
        for _ in range(2):
            with pytest.raises(InjectedDeviceFault):
                b.raise_if_injected("verify_batch")
            b.record_failure("injected")
        assert b.state == "open"
        assert not b.fault_injected  # quota spent + clock past window
        clock.t += 1.1
        assert b.allow()  # half-open probe
        b.raise_if_injected()  # disarmed: no-op
        b.record_success()
        assert b.state == "closed"

    def test_sim_device_crypto_full_cycle(self):
        """SimDeviceCrypto rides the real breaker state machine:
        injected faults fall back to exact host results, the breaker
        opens, a post-window probe closes it."""
        clock = FakeClock()
        m = Metrics()
        breaker = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                                 metrics=m, clock=clock)
        base = SimHashCrypto(b"\x42" * 32)
        crypto = SimDeviceCrypto(base, breaker=breaker, metrics=m)
        h = crypto.hash(b"payload")
        sig = crypto.sign(h)
        assert crypto.verify_signature(sig, h, crypto.pub_key)
        breaker.inject_faults(2.0)
        # results stay exact through the fallback while failures accrue
        assert crypto.verify_signature(sig, h, crypto.pub_key)
        assert not crypto.verify_signature(sig, crypto.hash(b"other"),
                                           crypto.pub_key)
        assert breaker.state == "open"
        # open: routed straight to host (no new failures)
        assert crypto.verify_signature(sig, h, crypto.pub_key)
        # past window + cooldown: half-open probe succeeds and closes
        clock.t += 2.5
        assert crypto.verify_signature(sig, h, crypto.pub_key)
        assert breaker.state == "closed"
        s = snapshot(m.registry)
        assert s.get("crypto_breaker_transitions_total{to=open}", 0) == 1
        assert s.get("crypto_breaker_transitions_total{to=closed}", 0) == 1
        assert s.get(
            "crypto_device_failures_total{path=verify_batch}", 0) == 2

    def test_aggregation_paths_also_gated(self):
        base = SimHashCrypto(b"\x43" * 32)
        crypto = SimDeviceCrypto(base)
        h = crypto.hash(b"vote")
        sig = crypto.sign(h)
        agg = crypto.aggregate_signatures([sig], [crypto.pub_key])
        assert crypto.verify_aggregated_signature(agg, h,
                                                  [crypto.pub_key])
        assert crypto.verify_batch([sig], [h], [crypto.pub_key]) == [True]


# ---------------------------------------------------------------------------
# Tenant chaos events (SharedFrontier attack windows)
# ---------------------------------------------------------------------------

class TestTenantChaos:
    @staticmethod
    def make_shared_net(metrics, queue_bound=64, **kw):
        """A fleet whose validators each feed a tenant lane on ONE
        SharedFrontier core (the sim/run.py --shared-frontier shape)."""
        from consensus_overlord_tpu.crypto.tenancy import SharedFrontier

        provider = SimHashCrypto(b"\x66" * 32)
        core = SharedFrontier(provider, max_batch=128, linger_s=0.002,
                              metrics=metrics)
        factory = lambda crypto: core.register(  # noqa: E731
            "v-" + crypto.pub_key[:4].hex(), queue_bound=queue_bound)
        net = make_net(metrics, frontier_factory=factory,
                       shared_frontier=core, **kw)
        return net, core

    def test_tenant_flood_sheds_and_rejects(self):
        async def main():
            m = Metrics()
            net, core = self.make_shared_net(m, queue_bound=64)
            net.start(init_height=1)
            heights = 5
            schedule = ChaosSchedule.generate(
                31, heights=heights, n_validators=4, crashes=0, stalls=0,
                partitions=0, tenant_floods=1, tenant_window_s=0.3)
            chaos = ChaosRunner(net, schedule)
            for h in range(1, heights + 1):
                await net.run_until_height(h, timeout=30)
            cap = net.controller.latest_height + 20
            while ((chaos.pending_count or chaos.inflight_count)
                   and net.controller.latest_height < cap):
                await net.run_until_height(
                    net.controller.latest_height + 1, timeout=30)
            await chaos.drain()
            await net.stop()
            core.close()
            await asyncio.sleep(0.05)
            assert not net.controller.violations
            floods = chaos.summary()["tenant_floods"]
            assert len(floods) == 1, chaos.summary()
            assert floods[0]["sheds"] > 0, floods
            assert floods[0]["rejected"] > 0, floods
            # shed accounting reached the metric surface too
            s = snapshot(m.registry)
            shed_total = sum(v for k, v in s.items()
                             if k.startswith(
                                 "frontier_admission_sheds_total"))
            assert shed_total >= floods[0]["sheds"]
        run(main(), timeout=180)

    def test_tenant_stall_backs_up_and_fleet_survives(self):
        async def main():
            m = Metrics()
            net, core = self.make_shared_net(m, queue_bound=64)
            net.start(init_height=1)
            heights = 5
            schedule = ChaosSchedule.generate(
                33, heights=heights, n_validators=4, crashes=0, stalls=0,
                partitions=0, tenant_stalls=1, tenant_window_s=0.3)
            chaos = ChaosRunner(net, schedule)
            for h in range(1, heights + 1):
                await net.run_until_height(h, timeout=30)
            await chaos.drain()
            await net.stop()
            core.close()
            await asyncio.sleep(0.05)
            assert not net.controller.violations
            assert net.controller.latest_height >= heights
            assert len(chaos.summary()["tenant_stalls"]) == 1
        run(main(), timeout=180)

    def test_tenant_events_skip_gracefully_without_shared_core(self):
        """On a fleet without a SharedFrontier the events log and skip
        — chaos must never crash the run it is stressing."""
        async def main():
            m = Metrics()
            net = make_net(m, seed=35)
            net.start(init_height=1)
            schedule = ChaosSchedule.generate(
                35, heights=4, n_validators=4, crashes=0, stalls=0,
                partitions=0, tenant_floods=1, tenant_stalls=1)
            chaos = ChaosRunner(net, schedule)
            for h in range(1, 5):
                await net.run_until_height(h, timeout=30)
            await chaos.drain()
            await net.stop()
            assert not net.controller.violations
            assert chaos.summary()["tenant_floods"] == []
        run(main())


# ---------------------------------------------------------------------------
# Router visibility (satellite: message loss must be attributable)
# ---------------------------------------------------------------------------

class TestRouterStats:
    def test_partition_drops_split_and_state_visible(self):
        async def main():
            m = Metrics()
            net = make_net(m, seed=17)
            net.start(init_height=1)
            await net.run_until_height(2, timeout=30)
            minority = {net.nodes[0].name}
            majority = {n.name for n in net.nodes} - minority
            net.router.set_partition(majority, minority)
            st = net.router.stats()
            assert st["partition_active"] and st["partition_flips"] == 1
            assert len(st["partitions"]) == 2
            await net.run_until_height(4, timeout=30)
            net.router.set_partition()
            st = net.router.stats()
            assert not st["partition_active"]
            assert st["dropped_partition"] >= 1
            assert st["dropped"] == (st["dropped_partition"]
                                     + st["dropped_loss"])
            await net.stop()
        run(main())
