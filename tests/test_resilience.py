"""Degraded-mode resilience: WAL integrity framing, the device circuit
breaker + host-oracle fallback, liveness-aware health, retry-client
transient/fatal split, and the seeded chaos harness (crash-restart +
controller faults + partition flips with the safety assertion).

This is the test surface for ISSUE 3's acceptance criteria: a corrupt
WAL recovers as fresh state with the original quarantined; a forced
device-dispatch failure re-verifies on the host oracle with correct
verdicts and the breaker recovers once the fault clears; Health flips
SERVING -> NOT_SERVING -> SERVING across an injected stall; a chaos
schedule commits its target heights with zero SafetyViolations."""

import asyncio
import os

import grpc
import pytest

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto.breaker import CircuitBreaker
from consensus_overlord_tpu.crypto.provider import CpuBlsCrypto, SimHashCrypto
from consensus_overlord_tpu.engine.wal import (
    CORRUPT_SUFFIX,
    OVERLORD_WAL_NAME,
    FileWal,
    MemoryWal,
    WalCorruption,
    frame_record,
    unframe_record,
)
from consensus_overlord_tpu.obs import Metrics, snapshot


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---------------------------------------------------------------------------
# WAL framing + quarantine
# ---------------------------------------------------------------------------

class TestWalFraming:
    def test_frame_roundtrip(self):
        payload = b"\x00\x01consensus-state\xff" * 7
        assert unframe_record(frame_record(payload)) == payload

    def test_unframe_rejects_each_corruption(self):
        blob = frame_record(b"payload-bytes")
        for bad in (
            blob[:-1],                      # truncated payload
            blob[:4],                       # truncated header
            b"RLP" + blob[3:],              # bad magic (legacy/foreign)
            blob[:4] + b"\x63" + blob[5:],  # unknown version
            blob[:-2] + bytes([blob[-2] ^ 0x40]) + blob[-1:],  # bit flip
            blob + b"trailing",             # length mismatch
        ):
            with pytest.raises(WalCorruption):
                unframe_record(bad)

    def test_file_wal_roundtrip(self, tmp_path):
        async def main():
            wal = FileWal(str(tmp_path / "w"))
            assert await wal.load() is None  # never saved
            await wal.save(b"state-1")
            await wal.save(b"state-2")      # overwrite-in-place semantics
            assert await wal.load() == b"state-2"
        run(main())

    @pytest.mark.parametrize("corruptor", [
        lambda blob: blob[: len(blob) // 2],          # torn write
        lambda blob: blob[:10] + bytes([blob[10] ^ 0x01]) + blob[11:],
        lambda blob: b"legacy unframed rlp payload",  # pre-framing file
    ], ids=["truncated", "bitflip", "legacy"])
    def test_file_wal_corruption_quarantined(self, tmp_path, corruptor):
        """A torn/bit-flipped/legacy WAL loads as None (fresh state) with
        the original file moved to overlord.wal.corrupt — never an
        unhandled exception."""
        async def main():
            m = Metrics()
            wal = FileWal(str(tmp_path / "w"), metrics=m)
            await wal.save(b"important-state")
            path = os.path.join(str(tmp_path / "w"), OVERLORD_WAL_NAME)
            with open(path, "rb") as f:
                blob = f.read()
            with open(path, "wb") as f:
                f.write(corruptor(blob))
            assert await wal.load() is None
            assert os.path.exists(path + CORRUPT_SUFFIX)
            assert not os.path.exists(path)  # moved, not copied
            assert wal.quarantined_path == path + CORRUPT_SUFFIX
            assert snapshot(m.registry)["wal_corruptions_total"] == 1.0
            # The next life saves + loads cleanly over the quarantine.
            await wal.save(b"fresh-state")
            assert await wal.load() == b"fresh-state"
        run(main())

    def test_file_wal_empty_file_is_fresh(self, tmp_path):
        async def main():
            wal = FileWal(str(tmp_path / "w"))
            path = os.path.join(str(tmp_path / "w"), OVERLORD_WAL_NAME)
            open(path, "wb").close()
            assert await wal.load() is None
            assert wal.quarantined_path is None  # nothing worth keeping
        run(main())

    def test_memory_wal_parity(self):
        """MemoryWal mirrors the framing semantics: engine tests that
        bit-flip `wal.data` exercise the production load path."""
        async def main():
            m = Metrics()
            wal = MemoryWal(metrics=m)
            await wal.save(b"mem-state")
            assert await wal.load() == b"mem-state"
            wal.data = wal.data[:-3]  # tear it
            assert await wal.load() is None
            assert wal.quarantined is not None
            assert wal.data is None
            assert snapshot(m.registry)["wal_corruptions_total"] == 1.0
            await wal.save(b"fresh")
            assert await wal.load() == b"fresh"
        run(main())

    def test_engine_restarts_from_corrupt_wal(self, tmp_path):
        """End-to-end acceptance: a validator whose WAL was corrupted
        on disk restarts as fresh state and keeps participating."""
        async def main():
            from consensus_overlord_tpu.sim import SimNetwork

            wal_dir = str(tmp_path / "wals")
            net = SimNetwork(
                n_validators=4, block_interval_ms=30,
                crypto_factory=lambda i: SimHashCrypto(bytes([i + 1]) * 32),
                wal_factory=lambda i: FileWal(f"{wal_dir}/node{i}"))
            net.start(init_height=1)
            await net.run_until_height(2)
            net.crash_node(0)
            # The cancelled engine may still have one in-flight WAL write
            # on a to_thread worker; let it land before tearing the file
            # or it would overwrite the corruption with a valid frame.
            await asyncio.sleep(0.2)
            path = os.path.join(wal_dir, "node0", OVERLORD_WAL_NAME)
            with open(path, "rb") as f:
                blob = f.read()
            with open(path, "wb") as f:
                f.write(blob[: len(blob) - 4])  # torn tail
            revived = net.restart_node(0)
            target = net.controller.latest_height + 3
            await net.run_until_height(target, timeout=20)
            await asyncio.sleep(0.2)
            assert os.path.exists(path + CORRUPT_SUFFIX)
            revived_heights = [h for (node, h, _) in
                               net.controller.commit_log
                               if node == revived.name]
            assert revived_heights and max(revived_heights) >= target - 1
            assert not net.controller.violations
            await net.stop()
        run(main())


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestCircuitBreaker:
    def test_opens_after_consecutive_failures(self):
        clock = FakeClock()
        b = CircuitBreaker(failure_threshold=3, cooldown_s=5.0, clock=clock)
        assert b.state == "closed" and b.allow()
        b.record_failure()
        b.record_failure()
        b.record_success()   # success resets the consecutive count
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"
        b.record_failure()
        assert b.state == "open"
        assert not b.allow()  # routed to host

    def test_half_open_probe_and_recovery(self):
        clock = FakeClock()
        b = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=clock)
        b.record_failure()
        assert b.state == "open" and not b.allow()
        clock.t += 5.1
        assert b.allow()          # the single half-open probe
        assert not b.allow()      # everyone else stays on host
        b.record_success()
        assert b.state == "closed" and b.allow()

    def test_failed_probe_reopens(self):
        clock = FakeClock()
        b = CircuitBreaker(failure_threshold=1, cooldown_s=5.0, clock=clock)
        b.record_failure()
        clock.t += 5.1
        assert b.allow()
        b.record_failure()        # probe failed
        assert b.state == "open"
        assert not b.allow()      # fresh cooldown
        clock.t += 5.1
        assert b.allow()          # next probe window

    def test_status_snapshot(self):
        b = CircuitBreaker(failure_threshold=1)
        b.record_failure("kaboom")
        st = b.status()
        assert st["state"] == "open" and st["times_opened"] == 1


# ---------------------------------------------------------------------------
# Injected device-dispatch failure -> host oracle fallback + recovery
# ---------------------------------------------------------------------------

class FlakyKernels:
    """Wraps a real kernel set; raises on every path while `fail` is
    set — the no-hardware-needed injected device fault."""

    lanes = 1

    def __init__(self, real):
        self.real = real
        self.fail = True
        self.calls = 0
        # operand feeding is not a device dispatch — never gated
        self.ship = real.ship
        self.ship_replicated = real.ship_replicated

    def _gate(self, name, *a):
        self.calls += 1
        if self.fail:
            raise RuntimeError("injected device fault")
        return getattr(self.real, name)(*a)

    def verify_round(self, *a):
        return self._gate("verify_round", *a)

    def verify_round_multi(self, *a):
        return self._gate("verify_round_multi", *a)

    def g1_validate_sum(self, *a):
        return self._gate("g1_validate_sum", *a)

    def g2_sum_rows(self, *a):
        return self._gate("g2_sum_rows", *a)

    def g2_validate(self, *a):
        return self._gate("g2_validate", *a)


N_BLS = 4
BLS_KEYS = [0x2222 * (i + 1) + 11 for i in range(N_BLS)]


@pytest.fixture(scope="module")
def bls_cpus():
    return [CpuBlsCrypto(k) for k in BLS_KEYS]


class TestDeviceFallback:
    def _flaky_provider(self, bls_cpus, **breaker_kw):
        from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto

        t = TpuBlsCrypto(BLS_KEYS[0], device_threshold=1,
                         qc_device_threshold=10**9,
                         breaker=CircuitBreaker(**breaker_kw))
        t.update_pubkeys([c.pub_key for c in bls_cpus])  # host path (qc thr)
        flaky = FlakyKernels(t._kernels)
        t._kernels = flaky
        return t, flaky

    def test_failed_dispatch_reverifies_on_host(self, bls_cpus):
        """The acceptance check: a forced device failure in a frontier
        batch produces the CORRECT verdicts via the host oracle, counts
        into the degraded-mode metrics, and trips the breaker."""
        clock = FakeClock()
        tpu, flaky = self._flaky_provider(
            bls_cpus, failure_threshold=2, cooldown_s=30.0, clock=clock)
        m = Metrics()
        tpu.bind_metrics(m)
        h = sm3_hash(b"degraded-block")
        sigs = [c.sign(h) for c in bls_cpus]
        voters = [c.pub_key for c in bls_cpus]
        sigs[1] = bls_cpus[1].sign(sm3_hash(b"other"))  # one bad lane
        want = [True, False, True, True]

        got = tpu.verify_batch(sigs, [h] * N_BLS, voters)
        assert got == want                  # exact verdicts, host oracle
        assert flaky.calls == 1
        scraped = snapshot(m.registry)
        assert scraped[
            "crypto_device_failures_total{path=verify_batch}"] == 1.0
        assert scraped[
            "crypto_host_fallbacks_total{path=verify_batch}"] == 1.0
        assert tpu.breaker.state == "closed"  # threshold 2: one more to trip

        assert tpu.verify_batch(sigs, [h] * N_BLS, voters) == want
        assert tpu.breaker.state == "open"
        scraped = snapshot(m.registry)
        assert scraped["crypto_breaker_open"] == 1.0
        assert scraped["crypto_breaker_transitions_total{to=open}"] == 1.0

        # Open breaker: no device traffic at all, still exact verdicts.
        assert tpu.verify_batch(sigs, [h] * N_BLS, voters) == want
        assert flaky.calls == 2

    def test_breaker_recovers_after_fault_clears(self, bls_cpus):
        clock = FakeClock()
        tpu, flaky = self._flaky_provider(
            bls_cpus, failure_threshold=1, cooldown_s=5.0, clock=clock)
        h = sm3_hash(b"recovery-block")
        sigs = [c.sign(h) for c in bls_cpus]
        voters = [c.pub_key for c in bls_cpus]

        assert tpu.verify_batch(sigs, [h] * N_BLS, voters) == [True] * N_BLS
        assert tpu.breaker.state == "open"
        flaky.fail = False                  # the chip comes back
        assert tpu.verify_batch(sigs, [h] * N_BLS, voters) == [True] * N_BLS
        assert tpu.breaker.state == "open"  # still cooling down: host path
        clock.t += 5.1
        # Half-open probe rides the real (restored) kernels and closes.
        assert tpu.verify_batch(sigs, [h] * N_BLS, voters) == [True] * N_BLS
        assert tpu.breaker.state == "closed"
        assert tpu.degraded_status()["times_opened"] == 1

    def test_frontier_reverifies_on_host_when_provider_errors(self):
        """A provider with NO internal breaker whose batch path dies:
        the frontier re-verifies every lane via verify_signature instead
        of dropping the batch as all-False."""
        from consensus_overlord_tpu.crypto.frontier import BatchingVerifier

        base = SimHashCrypto(b"\x07" * 32)

        class ExplodingBatch:
            pub_key = base.pub_key
            sign = base.sign
            verify_signature = staticmethod(base.verify_signature)

            @staticmethod
            def verify_batch(sigs, hashes, voters):
                raise RuntimeError("injected batch failure")

        async def main():
            m = Metrics()
            fr = BatchingVerifier(ExplodingBatch(), max_batch=8,
                                  linger_s=0.001, metrics=m)
            h = sm3_hash(b"payload")
            good = base.sign(h)
            ok, bad = await asyncio.gather(
                fr.verify(good, h, base.pub_key),
                fr.verify(b"\x00" * 32, h, base.pub_key))
            assert ok is True and bad is False
            scraped = snapshot(m.registry)
            assert scraped[
                "crypto_host_fallbacks_total{path=frontier_reverify}"] == 1.0
            fr.close()
        run(main())


# ---------------------------------------------------------------------------
# Liveness-aware health
# ---------------------------------------------------------------------------

class StubEngine:
    def __init__(self):
        self.height = 5
        self.running = True


class TestHealthLiveness:
    def test_serving_notserving_serving_across_stall(self):
        """The SERVING -> NOT_SERVING -> SERVING flip across an injected
        stall, against a fake clock."""
        from consensus_overlord_tpu.service.pb import pb2
        from consensus_overlord_tpu.service.server import HealthServer

        async def main():
            clock = FakeClock()
            eng = StubEngine()
            hs = HealthServer(engine=eng, stall_window_s=10.0, clock=clock)
            req = pb2.HealthCheckRequest()

            async def check():
                return (await hs.check(req, None)).status

            SERVING = pb2.HealthCheckResponse.SERVING
            NOT_SERVING = pb2.HealthCheckResponse.NOT_SERVING
            assert await check() == SERVING      # baseline established
            clock.t += 9.0
            assert await check() == SERVING      # inside the window
            clock.t += 2.0
            assert await check() == NOT_SERVING  # stalled past window
            assert hs.status()["serving"] is False
            eng.height += 1                      # the engine moves again
            assert await check() == SERVING
            clock.t += 11.0
            assert await check() == NOT_SERVING  # stalls again
        run(main())

    def test_not_running_engine_is_serving(self):
        """Startup (waiting for the controller's configuration) is not a
        stall — Docker must not restart a node that isn't wired yet."""
        from consensus_overlord_tpu.service.pb import pb2
        from consensus_overlord_tpu.service.server import HealthServer

        async def main():
            clock = FakeClock()
            eng = StubEngine()
            eng.running = False
            hs = HealthServer(engine=eng, stall_window_s=1.0, clock=clock)
            clock.t += 100.0
            resp = await hs.check(pb2.HealthCheckRequest(), None)
            assert resp.status == pb2.HealthCheckResponse.SERVING
        run(main())

    def test_disabled_window_always_serving(self):
        from consensus_overlord_tpu.service.pb import pb2
        from consensus_overlord_tpu.service.server import HealthServer

        async def main():
            clock = FakeClock()
            hs = HealthServer(engine=StubEngine(), stall_window_s=0.0,
                              clock=clock)
            clock.t += 10_000.0
            resp = await hs.check(pb2.HealthCheckRequest(), None)
            assert resp.status == pb2.HealthCheckResponse.SERVING
        run(main())


# ---------------------------------------------------------------------------
# Retry client: transient vs fatal
# ---------------------------------------------------------------------------

class TestRetrySplit:
    def test_transient_code_classification(self):
        from consensus_overlord_tpu.service.rpc import is_transient

        assert is_transient(grpc.StatusCode.UNAVAILABLE)
        assert is_transient(grpc.StatusCode.DEADLINE_EXCEEDED)
        assert not is_transient(grpc.StatusCode.INVALID_ARGUMENT)
        assert not is_transient(grpc.StatusCode.UNIMPLEMENTED)
        assert not is_transient(grpc.StatusCode.PERMISSION_DENIED)

    def test_backoff_grows_and_caps(self):
        from consensus_overlord_tpu.service.rpc import RetryClient

        client = RetryClient.__new__(RetryClient)  # no channel needed
        client._delay, client._max_delay = 0.3, 5.0
        import random as _random
        client._rng = _random.Random(42)
        delays = [client._backoff_s(a) for a in range(8)]
        # Exponential base, ±50% jitter, capped at max_delay * 1.5.
        for a, d in enumerate(delays):
            base = min(0.3 * 2 ** a, 5.0)
            assert base * 0.5 <= d <= base * 1.5

    def test_brain_error_carries_transient_flag(self):
        from consensus_overlord_tpu.service.brain import BrainError, _wrap_rpc

        class StubRpcError:
            def __init__(self, code):
                self._code = code

            def code(self):
                return self._code

        e = _wrap_rpc("get_proposal",
                      StubRpcError(grpc.StatusCode.UNAVAILABLE))
        assert isinstance(e, BrainError) and e.transient
        e = _wrap_rpc("get_proposal",
                      StubRpcError(grpc.StatusCode.INVALID_ARGUMENT))
        assert not e.transient
        assert BrainError("plain").transient  # default: retry-later


# ---------------------------------------------------------------------------
# Chaos harness
# ---------------------------------------------------------------------------

class TestChaosHarness:
    def test_schedule_generation_is_deterministic(self):
        from consensus_overlord_tpu.sim import ChaosSchedule

        a = ChaosSchedule.generate(7, heights=12, n_validators=4)
        b = ChaosSchedule.generate(7, heights=12, n_validators=4)
        c = ChaosSchedule.generate(8, heights=12, n_validators=4)
        assert a.events == b.events
        assert a.events != c.events
        kinds = sorted(e.kind for e in a.events)
        assert kinds == ["crash", "crash", "partition", "stall"]
        crash_nodes = [e.node for e in a.events if e.kind == "crash"]
        assert len(set(crash_nodes)) == 2  # distinct targets
        assert all(2 <= e.at_height <= 11 for e in a.events)

    def test_chaos_run_reconverges_with_zero_violations(self, tmp_path):
        """The sim/run.py --chaos acceptance slice, in-process: seeded
        crash-restart of 2 validators (FileWal recovery), a controller
        stall window, and a partition flip — the chain still reaches the
        target with no SafetyViolation, and every crashed node commits
        again after its restart."""
        async def main():
            from consensus_overlord_tpu.sim import (
                ChaosRunner,
                ChaosSchedule,
                SimNetwork,
            )

            heights = 8
            wal_dir = str(tmp_path / "wals")
            net = SimNetwork(
                n_validators=4, block_interval_ms=30,
                crypto_factory=lambda i: SimHashCrypto(bytes([i + 1]) * 32),
                wal_factory=lambda i: FileWal(f"{wal_dir}/node{i}"),
                flight_recorder_capacity=128)
            net.start(init_height=1)
            schedule = ChaosSchedule.generate(
                11, heights=heights, n_validators=4, crashes=2, stalls=1,
                partitions=1, downtime_s=0.15, window_s=0.15)
            chaos = ChaosRunner(net, schedule)
            try:
                for h in range(1, heights + 1):
                    await net.run_until_height(h, timeout=30)
                await chaos.drain()
                # Post-fault runway: everyone participates again.
                final = net.controller.latest_height + 2
                await net.run_until_height(final, timeout=30)
                await asyncio.sleep(0.2)
            except Exception:
                print(net.dump_flight_recorders(32))
                raise
            assert not net.controller.violations
            assert chaos.summary()["events_fired"] == 4
            crashed = [e.node for e in schedule.events if e.kind == "crash"]
            for i in crashed:
                name = net.nodes[i].name
                revived_heights = [h for (node, h, _) in
                                   net.controller.commit_log
                                   if node == name]
                assert revived_heights and max(revived_heights) > heights, \
                    f"crashed node {i} never committed after restart"
            await net.stop()
        run(main(), timeout=90)


# ---------------------------------------------------------------------------
# Soak-chaos survival lane (sim/run.py --soak-chaos)
# ---------------------------------------------------------------------------

class TestSoakChaosLane:
    def test_soak_chaos_cli_end_to_end(self, tmp_path):
        """The whole --soak-chaos surface through the real CLI at smoke
        length: recurring seeded cycles against a SharedFrontier fleet,
        telemetry sampled throughout, the drift gate evaluated, and a
        ledger-valid soak-chaos-survival BenchRecord written."""
        import json
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        record = tmp_path / "soak_record.json"
        samples = tmp_path / "samples.jsonl"
        out = subprocess.run(
            [sys.executable, "-m", "consensus_overlord_tpu.sim.run",
             "--validators", "4", "--heights", "2", "--interval-ms", "40",
             "--crypto", "simhash", "--chaos", "--seed", "5",
             "--chaos-crashes", "1", "--chaos-stalls", "0",
             "--chaos-partitions", "0", "--chaos-adaptive", "1",
             "--chaos-tenant-floods", "1", "--shared-frontier",
             "--soak-chaos", "--soak-seconds", "8",
             "--sample-every", "0.5",
             "--soak-out", str(samples), "--soak-record", str(record),
             # warmup RSS growth over an 8 s window is all slope; the
             # gate under test is the plumbing, not the ceiling values
             "--soak-max-rss-slope-mb", "512"],
            capture_output=True, text=True, timeout=300, cwd=repo,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
        lines = [ln for ln in out.stdout.splitlines()
                 if ln.startswith("{")]
        rec = json.loads(lines[0])     # the soak record line
        summary = json.loads(lines[-1])
        assert rec["metric"] == "soak-chaos-survival"
        assert rec["unit"] == "heights/s" and rec["value"] > 0
        assert rec["soak"]["safety_violations"] == 0
        assert rec["soak"]["chaos_cycles"] >= 1
        assert rec["drift_failures"] == []
        sc = summary["soak_chaos"]
        assert sc["soak_heights"] > 0
        assert summary["adversary"].get("adaptive_switch", 0) > 0
        floods = [f for c in sc["cycles"] for f in c["tenant_floods"]]
        assert floods and all(f["sheds"] > 0 for f in floods), floods
        assert summary["telemetry"]["samples"] >= 5
        assert summary["frontier_batches"] > 0  # rode the shared core
        # the record round-trips through the ledger (trend/check food)
        from consensus_overlord_tpu.obs import ledger

        loaded = ledger.load_record(json.load(open(record)), run="soak")
        assert loaded.soak["commit_rate_heights_per_s"] > 0
        assert samples.exists() and samples.read_text().count("\n") >= 5

    def test_liveness_failure_dump_includes_telemetry_trend(self):
        """The exit(2) post-mortem bugfix: a run that misses its height
        target must dump the telemetry trend block alongside the flight
        recorders (soak post-mortems need the drift series)."""
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # 2 validators cannot survive a crash (no quorum while down,
        # and n=2 tolerates f=0 anyway): the run wedges and must exit 2
        # with the full forensic dump.
        out = subprocess.run(
            [sys.executable, "-m", "consensus_overlord_tpu.sim.run",
             "--validators", "2", "--heights", "4", "--interval-ms", "40",
             "--crypto", "simhash", "--chaos", "--seed", "3",
             "--chaos-crashes", "2", "--chaos-stalls", "0",
             "--chaos-partitions", "0",
             "--chaos-downtime-ms", "30000",
             "--sample-every", "0.5", "--timeout", "6"],
            capture_output=True, text=True, timeout=300, cwd=repo,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 2, (out.returncode, out.stderr[-800:])
        assert "LIVENESS FAILURE" in out.stderr
        assert "telemetry trend:" in out.stderr
        assert "chaos summary:" in out.stderr
