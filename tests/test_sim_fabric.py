"""Sharded sim fabric tests (sim/router.py "Sharded fabric").

Pins the three contracts the fleet work leans on:

* address hygiene — bytearray/memoryview senders normalize to bytes at
  the fabric boundary, so broadcast never self-delivers and partition
  groups expressed over non-bytes names still cut traffic;
* the seed determinism contract at S>1 — same seed + same topology ⇒
  identical drop/delay/partition counters at ANY shard count, pinned
  against the golden fixture tests/data/router_golden_seed7.json;
* fleet plumbing — trunk batching, per-tick batch counters (the task
  churn criterion), sticky shard homing across crash/restart, and the
  thread worker mode matching inline's decision stream.
"""

import asyncio
import json
import pathlib

import pytest

from consensus_overlord_tpu.sim import SimNetwork
from consensus_overlord_tpu.sim.router import Router, ShardedRouter

GOLDEN = pathlib.Path(__file__).parent / "data" / "router_golden_seed7.json"

#: The counters the determinism contract covers (stats() keys).
COUNTER_KEYS = ("enqueued", "delivered", "dropped", "dropped_partition",
                "dropped_loss")


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _mknodes(n):
    return [bytes([i + 1]) * 8 for i in range(n)]


async def _drain(router, timeout=10.0):
    """Wait until everything admitted to the heap has been delivered
    (drop decisions are made at admission, so enqueued == delivered
    once the pumps go idle and nobody unregistered mid-flight)."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        s = router.stats()
        if s["delivered"] >= s["enqueued"]:
            return
        if loop.time() > deadline:
            raise AssertionError(f"fabric did not drain: {s}")
        await asyncio.sleep(0.01)


async def _scripted_traffic(router, nodes, counts):
    """The golden workload: broadcasts, a partition window, point-to-
    point sends, and a crash/re-register cycle.  Every admission happens
    in a deterministic order, so the drop/partition counters depend only
    on the seed — never on shard count or pump interleaving."""
    def handler_for(addr):
        async def handler(sender, msg_type, payload):
            counts[addr] = counts.get(addr, 0) + 1
        return handler

    for a in nodes:
        router.register(a, handler_for(a))

    # Phase A: five all-to-all broadcast rounds.
    for r in range(5):
        for a in nodes:
            await router.broadcast(a, "vote", b"ping%d" % r)
    await _drain(router)

    # Phase B: partition {0..5} vs {6,7}; cross-group traffic must be
    # cut (dropped_partition), intra-group traffic still flows.
    router.set_partition(set(nodes[:6]), set(nodes[6:]))
    for r in range(2):
        for a in nodes:
            await router.broadcast(a, "vote", b"cut%d" % r)
    router.set_partition()  # heal
    await _drain(router)

    # Phase C: point-to-point ring sends.
    for r in range(10):
        for i, a in enumerate(nodes):
            await router.send(a, nodes[(i + 3) % len(nodes)],
                              "choke", b"p2p%d" % r)
    await _drain(router)

    # Phase D: crash node 3 (unregister), broadcast — deliveries to the
    # dead address are refused at admission; then revive and go again.
    router.unregister(nodes[3])
    await router.broadcast(nodes[0], "status", b"while-down")
    await _drain(router)
    router.register(nodes[3], handler_for(nodes[3]))
    await router.broadcast(nodes[0], "status", b"back-up")
    await _drain(router)


def _run_script(shards, seed=7, worker="inline"):
    async def main():
        router = ShardedRouter(seed=seed, drop_rate=0.2,
                               delay_range=(0.0, 0.005), shards=shards,
                               worker=worker)
        counts = {}
        nodes = _mknodes(8)
        try:
            await _scripted_traffic(router, nodes, counts)
            stats = router.stats()
        finally:
            router.close()
        return stats, counts
    return run(main())


class TestAddressHygiene:
    """Satellite: the bytearray-sender bug.  Before normalization a
    bytearray sender compared unequal to its registered bytes key, so
    broadcast self-delivered and partition groups leaked."""

    def test_bytearray_sender_does_not_self_deliver(self):
        async def main():
            router = Router(seed=1)
            got = {}

            def mk(addr):
                async def h(sender, msg_type, payload):
                    got[addr] = got.get(addr, 0) + 1
                return h

            a, b = b"\x01" * 8, b"\x02" * 8
            router.register(a, mk(a))
            router.register(b, mk(b))
            await router.broadcast(bytearray(a), "vote", b"x")
            await _drain(router)
            router.close()
            assert got == {b: 1}, got  # never back to the sender
        run(main())

    def test_memoryview_addresses_normalize(self):
        async def main():
            router = ShardedRouter(seed=1, shards=2)
            got = {}

            async def h(sender, msg_type, payload):
                got[bytes(sender)] = got.get(bytes(sender), 0) + 1

            a, b = b"\x01" * 8, b"\x02" * 8
            router.register(memoryview(a), h)
            router.register(bytearray(b), h)
            # Same home shard whatever the spelling of the address.
            assert router.shard_of(a) == router.shard_of(memoryview(a))
            await router.send(memoryview(a), bytearray(b), "vote", b"x")
            await _drain(router)
            router.close()
            assert got == {a: 1}
        run(main())

    def test_partition_groups_accept_bytearray_members(self):
        async def main():
            router = Router(seed=1)
            got = []

            async def h(sender, msg_type, payload):
                got.append(bytes(sender))

            a, b = b"\x01" * 8, b"\x02" * 8
            router.register(a, h)
            router.register(b, h)
            # bytearray is unhashable, so groups arrive as plain lists;
            # the fabric normalizes members to bytes sets internally.
            router.set_partition([bytearray(a)], [bytearray(b)])
            await router.send(a, b, "vote", b"cut")
            await _drain(router)
            assert router.stats()["dropped_partition"] == 1
            assert got == []
            router.set_partition()
            await router.send(a, b, "vote", b"ok")
            await _drain(router)
            router.close()
            assert got == [a]
        run(main())


class TestSeedDeterminism:
    """Tentpole contract: same seed + same topology ⇒ identical
    drop/delay/partition decisions at any shard count."""

    def test_one_vs_four_shards_match_golden(self):
        s1, c1 = _run_script(shards=1)
        s4, c4 = _run_script(shards=4)
        for k in COUNTER_KEYS:
            assert s1[k] == s4[k], (k, s1[k], s4[k])
        # Per-target delivery counts match too, not just totals.
        assert c1 == c4
        # Shard layout sanity: S=1 never rides the trunk, S=4 must.
        assert s1["trunk_msgs"] == 0
        assert s4["trunk_msgs"] > 0
        assert s4["trunk_drains"] > 0
        golden = json.loads(GOLDEN.read_text())
        assert golden["seed"] == 7
        for k in COUNTER_KEYS:
            assert s4[k] == golden["counters"][k], \
                (k, s4[k], golden["counters"][k])

    def test_different_seed_diverges(self):
        s7, _ = _run_script(shards=4, seed=7)
        s8, _ = _run_script(shards=4, seed=8)
        # Same workload, different key: the loss pattern must change
        # (equal dropped_loss for two seeds would mean the seed is dead).
        assert s7["dropped_loss"] != s8["dropped_loss"]

    def test_thread_worker_matches_inline_decisions(self):
        """Decisions happen at admission on the loop, so the thread
        pump must produce the same drop/partition counters as inline."""
        si, ci = _run_script(shards=4, worker="inline")
        st, ct = _run_script(shards=4, worker="thread")
        for k in ("enqueued", "dropped", "dropped_partition",
                  "dropped_loss"):
            assert si[k] == st[k], (k, si[k], st[k])
        assert ci == ct


class TestFleetPlumbing:
    def test_tick_batching_beats_task_per_message(self):
        """The churn criterion: a same-slice flood must coalesce into
        few pump passes (>=8x fewer scheduling units than messages)."""
        async def main():
            router = ShardedRouter(seed=3, shards=2)
            seen = []

            async def h(sender, msg_type, payload):
                seen.append(payload)

            nodes = _mknodes(8)
            for a in nodes:
                router.register(a, h)
            for r in range(50):
                await router.broadcast(nodes[0], "vote", b"f%d" % r)
            await _drain(router)
            stats = router.stats()
            router.close()
            assert stats["delivered"] == 50 * 7
            assert stats["task_churn_reduction"] >= 8, stats
            assert stats["max_tick_batch"] >= 8
        run(main())

    def test_sticky_homing_across_restart(self):
        """Crash/restart lands a validator back on its home shard, so
        a mid-soak revival never reshuffles the fleet layout."""
        async def main():
            router = ShardedRouter(seed=3, shards=4)
            nodes = _mknodes(8)

            async def h(sender, msg_type, payload):
                pass

            for a in nodes:
                router.register(a, h)
            homes = [router.shard_of(a) for a in nodes]
            assert sorted(set(homes)) == [0, 1, 2, 3]  # round-robin
            router.unregister(nodes[5])
            router.register(nodes[5], h)
            assert router.shard_of(nodes[5]) == homes[5]
            # New address after the fleet formed still gets a home.
            late = b"\x63" * 8
            router.register(late, h)
            assert 0 <= router.shard_of(late) < 4
            router.close()
        run(main())

    def test_crash_restart_across_shards_keeps_committing(self):
        """SimNetwork end-to-end on a 4-shard fabric: crash a node,
        restart it, and the fleet reaches the target height with zero
        safety violations and the node back on its original shard."""
        async def main():
            net = SimNetwork(n_validators=8, block_interval_ms=50,
                             seed=7, shards=4)
            assert net.router.n_shards == 4
            net.start(init_height=1)
            await net.run_until_height(2)
            victim = net.nodes[2]
            home = net.router.shard_of(victim.name)
            await victim.stop()
            await net.run_until_height(net.controller.latest_height + 2)
            revived = net.restart_node(2)
            revived.start(net.controller.latest_height + 1,
                          net.controller.block_interval_ms,
                          net.controller.authority_list())
            assert net.router.shard_of(revived.name) == home
            target = net.controller.latest_height + 3
            await net.run_until_height(target, timeout=30)
            await asyncio.sleep(0.3)
            revived_heights = [h for (node, h, _) in
                               net.controller.commit_log
                               if node == revived.name]
            assert revived_heights and max(revived_heights) > target - 3
            assert net.controller.violations == []
            stats = net.router.stats()
            assert stats["trunk_msgs"] > 0  # traffic crossed shards
            await net.stop()
        run(main())

    def test_rolling_partition_spans_shards(self):
        """Chaos partition events at S>1 sweep the isolated minority
        across sub-windows (sim/chaos.py): each minority is f
        consecutive validators, which straddles shard boundaries under
        round-robin homing."""
        async def main():
            net = SimNetwork(n_validators=8, block_interval_ms=50,
                             seed=7, shards=4)
            net.start(init_height=1)
            await net.run_until_height(2)
            # f=2 consecutive validators under round-robin homing always
            # live on two different shards.
            names = [n.name for n in net.nodes]
            minority = set(names[:2])
            shards_hit = {net.router.shard_of(a) for a in minority}
            assert len(shards_hit) == 2
            net.router.set_partition(set(names) - minority, minority)
            assert net.router.partition_active
            await net.run_until_height(net.controller.latest_height + 2,
                                       timeout=30)
            net.router.set_partition()
            await net.run_until_height(net.controller.latest_height + 1)
            assert net.controller.violations == []
            await net.stop()
        run(main())
