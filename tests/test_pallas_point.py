"""Fused Pallas point kernels vs the XLA curve ops — bit-identical
outputs (the kernels replay the same straight-line formulas and the same
statically planned reductions; on CPU they run in interpret mode)."""

import random

import jax
import jax.numpy as jnp
import numpy as np

from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.ops import bls12381_groups as dev
from consensus_overlord_tpu.ops.curve import Point
from consensus_overlord_tpu.ops.pallas_point import (
    g1_add_transposed, g1_dbl_transposed)

RNG = random.Random(0xF00D)
B = 256  # one block tile


def rand_points(k):
    return [oracle.g1_mul(oracle.G1_GEN, RNG.randrange(oracle.R))
            for _ in range(k)]


def to_t(coord):
    return jnp.moveaxis(coord, 0, 1)


def test_fused_add_matches_xla():
    pts_a = dev.g1_from_oracle(rand_points(B - 2) + [None, None])
    pts_b = dev.g1_from_oracle(rand_points(B))
    want = jax.jit(dev.G1.add)(pts_a, pts_b)
    fn = g1_add_transposed(dev.FQ if not hasattr(dev.FQ, "_spec")
                           else dev.FQ._spec)
    got = fn(to_t(pts_a.x), to_t(pts_a.y), to_t(pts_a.z),
             to_t(pts_b.x), to_t(pts_b.y), to_t(pts_b.z))
    for g, w in zip(got, want):
        assert np.array_equal(np.asarray(g), np.asarray(to_t(w))), \
            "fused add not bit-identical to XLA path"


def test_fused_dbl_matches_xla():
    pts = dev.g1_from_oracle(rand_points(B - 1) + [None])
    want = jax.jit(dev.G1.dbl)(pts)
    fn = g1_dbl_transposed(dev.FQ if not hasattr(dev.FQ, "_spec")
                           else dev.FQ._spec)
    got = fn(to_t(pts.x), to_t(pts.y), to_t(pts.z))
    for g, w in zip(got, want):
        assert np.array_equal(np.asarray(g), np.asarray(to_t(w))), \
            "fused dbl not bit-identical to XLA path"


def test_fused_chain_matches_oracle():
    """A chain of fused ops (dbl, add) stays on the curve and equals the
    oracle: 2·(2P + Q) for random P, Q."""
    p_aff = rand_points(8)
    q_aff = rand_points(8)
    p = dev.g1_from_oracle(p_aff)
    q = dev.g1_from_oracle(q_aff)
    spec = dev.FQ if not hasattr(dev.FQ, "_spec") else dev.FQ._spec
    add = g1_add_transposed(spec, block_b=8)
    dbl = g1_dbl_transposed(spec, block_b=8)
    px, py, pz = to_t(p.x), to_t(p.y), to_t(p.z)
    qx, qy, qz = to_t(q.x), to_t(q.y), to_t(q.z)
    dx, dy, dz = dbl(px, py, pz)
    sx, sy, sz = add(dx, dy, dz, qx, qy, qz)
    fx, fy, fz = dbl(sx, sy, sz)
    got = dev.g1_to_oracle(Point(jnp.moveaxis(fx, 0, 1),
                                 jnp.moveaxis(fy, 0, 1),
                                 jnp.moveaxis(fz, 0, 1)))
    want = [oracle.g1_mul(oracle.g1_add(oracle.g1_add(pp, pp), qq), 2)
            for pp, qq in zip(p_aff, q_aff)]
    assert got == want
