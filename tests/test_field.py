"""ops/field.py against exact Python big-int arithmetic (the oracle)."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_overlord_tpu.ops.field import (
    BLS12_381_FQ, BLS12_381_P, ED25519_P, SECP256K1_P, SM2_P, FieldSpec)

F = BLS12_381_FQ
P = BLS12_381_P
RNG = random.Random(0xF1E1D)


def rand_elems(k):
    return [RNG.randrange(P) for _ in range(k)]


def loosen(spec, v):
    """A random non-canonical loose representation of v (limbs up to
    loose_max), to prove ops accept the full loose domain."""
    digits = list(spec.from_int(v % spec.p).astype(int))
    for _ in range(200):
        i = RNG.randrange(spec.n - 1)
        room = spec.loose_max - digits[i]
        if digits[i + 1] >= 1 and room >= (1 << spec.b):
            digits[i] += 1 << spec.b
            digits[i + 1] -= 1
    return np.array(digits, dtype=np.int32)


class TestConversions:
    def test_roundtrip(self):
        vals = [0, 1, 2, P - 1, P // 2] + rand_elems(16)
        x = jnp.asarray(F.from_ints(vals))
        assert F.to_ints(x) == [v % P for v in vals]

    def test_loose_roundtrip(self):
        vals = rand_elems(8)
        x = jnp.asarray(np.stack([loosen(F, v) for v in vals]))
        assert int(np.max(np.asarray(x))) > F.mask  # actually loose
        assert F.to_ints(x) == vals


class TestArithmetic:
    def test_add_sub_mul_batch(self):
        a = rand_elems(32)
        b = rand_elems(32)
        xa = jnp.asarray(np.stack([loosen(F, v) for v in a]))
        xb = jnp.asarray(np.stack([loosen(F, v) for v in b]))
        assert F.to_ints(F.add(xa, xb)) == [(u + v) % P for u, v in zip(a, b)]
        assert F.to_ints(F.sub(xa, xb)) == [(u - v) % P for u, v in zip(a, b)]
        assert F.to_ints(F.mul(xa, xb)) == [(u * v) % P for u, v in zip(a, b)]
        assert F.to_ints(F.neg(xa)) == [(-u) % P for u in a]
        assert F.to_ints(F.sq(xa)) == [u * u % P for u in a]

    def test_edge_values(self):
        edges = [0, 1, P - 1, P - 2, (P - 1) // 2, (P + 1) // 2]
        for u in edges:
            for v in edges:
                xu, xv = jnp.asarray(F.from_int(u)), jnp.asarray(F.from_int(v))
                assert F.to_int(F.mul(xu, xv)) == u * v % P
                assert F.to_int(F.add(xu, xv)) == (u + v) % P
                assert F.to_int(F.sub(xu, xv)) == (u - v) % P

    def test_all_max_loose_limbs(self):
        """Adversarial worst case: every limb at loose_max on both inputs."""
        digits = np.full((F.n,), F.loose_max, dtype=np.int32)
        v = sum(int(d) << (F.b * i) for i, d in enumerate(digits)) % P
        x = jnp.asarray(digits)
        assert F.to_int(F.mul(x, x)) == v * v % P
        assert F.to_int(F.add(x, x)) == 2 * v % P
        assert F.to_int(F.sub(x, x)) == 0

    def test_mul_formulations_agree(self, monkeypatch):
        """Both convolution formulations (staircase: CPU compile-speed
        path; padsum: the TPU runtime path) must stay bit-equivalent to
        each other AND to big-int math — on CPU CI the auto-select only
        ever traces staircase, so without this the padsum branch the
        production chip executes would have zero coverage."""
        vals = rand_elems(8)
        ws = rand_elems(8)
        x = jnp.asarray(np.stack([F.from_int(v) for v in vals]))
        y = jnp.asarray(np.stack([F.from_int(w) for w in ws]))
        outs = {}
        for form in ("staircase", "padsum"):
            monkeypatch.setenv("CONSENSUS_FIELD_MUL", form)
            outs[form] = np.asarray(F.strict(F.mul(x, y)))
        assert np.array_equal(outs["staircase"], outs["padsum"])
        got = F.ints_from_strict(outs["padsum"])
        assert got == [v * w % P for v, w in zip(vals, ws)]
        monkeypatch.setenv("CONSENSUS_FIELD_MUL", "typo")
        with pytest.raises(ValueError):
            F.mul(x, y)

    def test_mul_small(self):
        a = rand_elems(8)
        xa = jnp.asarray(F.from_ints(a))
        for k in (0, 1, 2, 3, 4, 12, 1000):
            assert F.to_ints(F.mul_small(xa, k)) == [u * k % P for u in a]

    def test_chained_ops_stay_loose(self):
        """Outputs of ops must be legal inputs to further ops (loose domain
        closure) — run a deep random chain and compare against the oracle."""
        a, b = rand_elems(2)
        x, y = jnp.asarray(F.from_int(a)), jnp.asarray(F.from_int(b))
        va, vb = a, b
        for i in range(50):
            op = RNG.choice(["add", "sub", "mul", "sq"])
            if op == "add":
                x, va = F.add(x, y), (va + vb) % P
            elif op == "sub":
                x, va = F.sub(x, y), (va - vb) % P
            elif op == "mul":
                x, va = F.mul(x, y), (va * vb) % P
            else:
                y, vb = F.sq(y), vb * vb % P
            assert int(np.max(np.abs(np.asarray(x)))) <= F.loose_max
        assert F.to_int(x) == va
        assert F.to_int(y) == vb


class TestPowInvSqrt:
    def test_pow(self):
        a = rand_elems(4)
        xa = jnp.asarray(F.from_ints(a))
        for e in (1, 2, 3, 65537, RNG.randrange(P)):
            assert F.to_ints(F.pow_static(xa, e)) == [pow(u, e, P) for u in a]

    def test_inv(self):
        a = [1, 2, P - 1] + rand_elems(5)
        xa = jnp.asarray(F.from_ints(a))
        assert F.to_ints(F.inv(xa)) == [pow(u, -1, P) for u in a]

    def test_inv_zero(self):
        assert F.to_int(F.inv(jnp.asarray(F.from_int(0)))) == 0

    def test_sqrt(self):
        squares = [pow(u, 2, P) for u in rand_elems(6)]
        xs = jnp.asarray(F.from_ints(squares))
        roots = F.to_ints(F.sqrt_candidate(xs))
        for r, s in zip(roots, squares):
            assert r * r % P == s


class TestPredicates:
    def test_is_zero_eq(self):
        a = rand_elems(4)
        xa = jnp.asarray(F.from_ints(a))
        assert list(np.asarray(F.is_zero(xa))) == [False] * 4
        zero_loose = F.sub(xa, jnp.asarray(np.stack(
            [loosen(F, v) for v in a])))
        assert list(np.asarray(F.is_zero(zero_loose))) == [True] * 4
        assert bool(F.eq(xa, jnp.asarray(F.from_ints(a))).all())

    def test_strict_matches_canonical(self):
        for v in [0, 1, P - 1] + rand_elems(4):
            x = jnp.asarray(loosen(F, v))
            got = np.asarray(F.strict(x)).astype(np.int64)
            want = F.from_int(v).astype(np.int64)
            assert (got == want).all()


class TestOtherModuli:
    @pytest.mark.parametrize("p", [ED25519_P, SECP256K1_P, SM2_P])
    def test_generic_modulus(self, p):
        spec = FieldSpec(p, limb_bits=10, name=f"f_{p % 1000}")
        a = [RNG.randrange(p) for _ in range(8)]
        b = [RNG.randrange(p) for _ in range(8)]
        xa, xb = jnp.asarray(spec.from_ints(a)), jnp.asarray(spec.from_ints(b))
        assert spec.to_ints(spec.mul(xa, xb)) == [
            (u * v) % p for u, v in zip(a, b)]
        assert spec.to_ints(spec.sub(xa, xb)) == [
            (u - v) % p for u, v in zip(a, b)]
        assert spec.to_ints(spec.inv(xa)) == [pow(u, -1, p) for u in a]


class TestJit:
    def test_ops_jit_and_vmap(self):
        a, b = rand_elems(16), rand_elems(16)
        xa, xb = jnp.asarray(F.from_ints(a)), jnp.asarray(F.from_ints(b))
        mul_j = jax.jit(F.mul)
        assert F.to_ints(mul_j(xa, xb)) == [(u * v) % P for u, v in zip(a, b)]
        mul_v = jax.vmap(F.mul)
        assert F.to_ints(mul_v(xa, xb)) == [(u * v) % P for u, v in zip(a, b)]
