"""secp256k1 / SM2: generic-a curve ops, dual-scalar MSM, providers.

The host oracle is ops-independent python-int affine math (HostCurve);
the secp256k1 ECDSA scheme is additionally cross-checked against the
`cryptography` package in both directions."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from consensus_overlord_tpu.crypto.ecdsa_tpu import (  # noqa: E402
    SECP_HOST, SM2_HOST, Secp256k1Crypto, Sm2Crypto)
from consensus_overlord_tpu.ops import weierstrass as w  # noqa: E402
from consensus_overlord_tpu.ops.curve import int_to_bits_msb  # noqa: E402

CASES = [(w.SECP, SECP_HOST), (w.SM2, SM2_HOST)]


def _dev_points(ops, host, scalars):
    pts = [host.mul(k, host.g) for k in scalars]
    f = ops.f
    x = jnp.asarray(np.stack([f.from_int(p[0]) for p in pts]))
    y = jnp.asarray(np.stack([f.from_int(p[1]) for p in pts]))
    return ops.from_affine(x, y), pts


def _affine_ints(ops, pt):
    ax, ay, ainf = ops.to_affine(pt)
    return [
        None if bool(i) else (xv, yv)
        for xv, yv, i in zip(ops.f.to_ints(ax), ops.f.to_ints(ay),
                             np.asarray(ainf).reshape(-1))
    ]


@pytest.mark.parametrize("ops,host", CASES, ids=["secp256k1", "sm2"])
def test_add_matches_host(ops, host):
    ks = [1, 2, 3, 12345, host.n - 1]
    p_dev, p_aff = _dev_points(ops, host, ks)
    q_dev, q_aff = _dev_points(ops, host, list(reversed(ks)))
    got = _affine_ints(ops, ops.add(p_dev, q_dev))
    want = [host.add(a, b) for a, b in zip(p_aff, q_aff)]
    assert got == want  # includes P + (−P): k + (n−k) = ∞ on lane pairs


@pytest.mark.parametrize("ops,host", CASES, ids=["secp256k1", "sm2"])
def test_dbl_and_identity(ops, host):
    p_dev, p_aff = _dev_points(ops, host, [5, 77])
    assert _affine_ints(ops, ops.dbl(p_dev)) == [
        host.add(a, a) for a in p_aff]
    inf = ops.infinity_like(p_dev.x)
    assert _affine_ints(ops, ops.add(p_dev, inf)) == p_aff
    assert bool(np.asarray(ops.is_infinity(inf)).all())


@pytest.mark.parametrize("ops,host", CASES, ids=["secp256k1", "sm2"])
def test_on_curve(ops, host):
    p_dev, _ = _dev_points(ops, host, [9, 10])
    assert bool(np.asarray(ops.on_curve(p_dev)).all())
    bad = p_dev._replace(x=ops.f.add(p_dev.x, ops.f.one()))
    assert not bool(np.asarray(ops.on_curve(bad)).any())


@pytest.mark.parametrize("ops,host", CASES, ids=["secp256k1", "sm2"])
def test_dual_scalar_mul(ops, host):
    rng = np.random.default_rng(7)
    u1s = [int.from_bytes(rng.bytes(32), "big") % host.n for _ in range(4)]
    u2s = [int.from_bytes(rng.bytes(32), "big") % host.n for _ in range(4)]
    u1s[3] = 0  # zero-scalar lane
    q_dev, q_aff = _dev_points(ops, host, [3, 8, 1, 4])
    f = ops.f
    g = ops.from_affine(
        jnp.asarray(f.from_int(host.g[0]))[None].astype(jnp.int32),
        jnp.asarray(f.from_int(host.g[1]))[None].astype(jnp.int32))
    got = _affine_ints(ops, w.dual_scalar_mul_bits(
        ops, g, int_to_bits_msb(u1s, 256), q_dev, int_to_bits_msb(u2s, 256)))
    want = [host.add(host.mul(u1, host.g), host.mul(u2, q))
            for u1, u2, q in zip(u1s, u2s, q_aff)]
    assert got == want


# -- providers ---------------------------------------------------------------

@pytest.mark.parametrize("cls", [Secp256k1Crypto, Sm2Crypto],
                         ids=["secp256k1", "sm2"])
def test_sign_verify_roundtrip(cls):
    c = cls(0xC0FFEE)
    h = c.hash(b"proposal")
    sig = c.sign(h)
    assert c.verify_signature(sig, h, c.pub_key)
    assert not c.verify_signature(sig, c.hash(b"other"), c.pub_key)
    bad = bytearray(sig)
    bad[5] ^= 1
    assert not c.verify_signature(bytes(bad), h, c.pub_key)
    other = cls(0xBEEF)
    assert not c.verify_signature(sig, h, other.pub_key)


def test_secp256k1_low_s_rule():
    c = Secp256k1Crypto(0xAB)
    h = c.hash(b"vote")
    sig = c.sign(h)
    s = int.from_bytes(sig[32:], "big")
    assert 2 * s <= SECP_HOST.n
    high = sig[:32] + (SECP_HOST.n - s).to_bytes(32, "big")
    assert not c.verify_signature(high, h, c.pub_key)  # one encoding only


def test_secp256k1_cross_check_cryptography():
    ec = pytest.importorskip(
        "cryptography.hazmat.primitives.asymmetric.ec")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed, decode_dss_signature, encode_dss_signature)

    ours = Secp256k1Crypto(0x1DEA)
    lib_sk = ec.derive_private_key(ours._sk, ec.SECP256K1())
    lib_pk = lib_sk.public_key()
    h = ours.hash(b"interop")

    # ours → lib
    sig = ours.sign(h)
    der = encode_dss_signature(int.from_bytes(sig[:32], "big"),
                               int.from_bytes(sig[32:], "big"))
    lib_pk.verify(der, h, ec.ECDSA(Prehashed(hashes.SHA256())))

    # lib → ours (normalized to the low-s form our verifier requires)
    der2 = lib_sk.sign(h, ec.ECDSA(Prehashed(hashes.SHA256())))
    r, s = decode_dss_signature(der2)
    s = min(s, SECP_HOST.n - s)
    sig2 = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    assert ours.verify_signature(sig2, h, ours.pub_key)

    # lib parses our compressed pubkey
    ec.EllipticCurvePublicKey.from_encoded_point(
        ec.SECP256K1(), ours.pub_key)


@pytest.mark.parametrize("cls", [Secp256k1Crypto, Sm2Crypto],
                         ids=["secp256k1", "sm2"])
def test_device_verify_batch(cls):
    signers = [cls(0x5000 + 13 * i, device_threshold=4) for i in range(6)]
    verifier = signers[0]
    hashes = [verifier.hash(bytes([i])) for i in range(6)]
    sigs = [s.sign(h) for s, h in zip(signers, hashes)]
    voters = [s.pub_key for s in signers]

    assert verifier.verify_batch(sigs, hashes, voters) == [True] * 6

    # corrupt lanes: flipped sig byte, wrong hash, swapped voter,
    # malformed voter, short sig
    bad_sigs = list(sigs)
    bad_sigs[1] = sigs[1][:5] + bytes([sigs[1][5] ^ 1]) + sigs[1][6:]
    bad_hashes = list(hashes)
    bad_hashes[2] = verifier.hash(b"nope")
    bad_voters = list(voters)
    bad_voters[3] = voters[4]
    bad_voters[5] = b"\x02" + b"\xff" * 32
    got = verifier.verify_batch(bad_sigs, bad_hashes, bad_voters)
    assert got == [True, False, False, False, True, False]


@pytest.mark.parametrize("cls", [Secp256k1Crypto, Sm2Crypto],
                         ids=["secp256k1", "sm2"])
def test_aggregate_roundtrip(cls):
    signers = [cls(0x7000 + 31 * i, device_threshold=4) for i in range(5)]
    v = signers[0]
    h = v.hash(b"qc")
    sigs = [s.sign(h) for s in signers]
    voters = [s.pub_key for s in signers]
    agg = v.aggregate_signatures(sigs, voters)
    assert v.verify_aggregated_signature(agg, h, voters)
    assert not v.verify_aggregated_signature(agg, v.hash(b"x"), voters)
    assert not v.verify_aggregated_signature(agg[:-1], h, voters)
    assert not v.verify_aggregated_signature(agg, h, [])
