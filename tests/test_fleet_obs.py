"""Fleet observability layer (obs/fleet.py + obs/anomaly.py): round-id
tagging through the frontier, the straggler detector (unit + the
8-lane virtual CPU mesh with an injected per-device sleep), the
cross-host fleet aggregator (degenerate mode + a real loopback peer
pull), telemetry startup rotation + the per-sample observer hook, the
EWMA anomaly detectors, and scripts/waterfall.py's round
reconstruction."""

import asyncio
import json
import os
import subprocess
import sys
import tempfile
import unittest
import urllib.request

import numpy as np

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto.frontier import BatchingVerifier
from consensus_overlord_tpu.crypto.provider import CpuBlsCrypto
from consensus_overlord_tpu.obs import (AnomalyDetector, DeviceProfiler,
                                        FleetAggregator, FlightRecorder,
                                        Metrics, StragglerDetector,
                                        TelemetrySampler, snapshot)
from consensus_overlord_tpu.obs.anomaly import EwmaSeries
from consensus_overlord_tpu.obs.fleet import (current_round_id,
                                              next_round_id, tag_round)

WATERFALL = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "..", "scripts", "waterfall.py")


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# round tagging
# ---------------------------------------------------------------------------

class RoundTagging(unittest.TestCase):
    def test_ids_monotonic(self):
        a, b = next_round_id(), next_round_id()
        self.assertGreater(b, a)

    def test_tag_round_sets_and_restores(self):
        self.assertIsNone(current_round_id())
        with tag_round(7):
            self.assertEqual(current_round_id(), 7)
            with tag_round(8):  # nests
                self.assertEqual(current_round_id(), 8)
            self.assertEqual(current_round_id(), 7)
        self.assertIsNone(current_round_id())

    def test_tag_is_thread_local(self):
        import threading

        seen = []
        with tag_round(42):
            t = threading.Thread(
                target=lambda: seen.append(current_round_id()))
            t.start()
            t.join()
        self.assertEqual(seen, [None])


class TaggedCrypto(CpuBlsCrypto):
    """Captures the round id visible INSIDE verify_batch — i.e. on the
    frontier's dispatch thread, where the provider's profiler hooks
    run."""

    def __init__(self, sk):
        super().__init__(sk)
        self.seen_round_ids = []

    def verify_batch(self, sigs, hashes, voters):
        self.seen_round_ids.append(current_round_id())
        return super().verify_batch(sigs, hashes, voters)


class FrontierRoundFlush(unittest.TestCase):
    def test_flush_records_round_and_tags_dispatch(self):
        """Each frontier flush draws a round id, records a round_flush
        flightrec event carrying it, and the provider's verify runs
        inside a tag_round scope with the same id."""
        async def main():
            crypto = TaggedCrypto(0xC0FFEE)
            rec = FlightRecorder(64)
            fr = BatchingVerifier(crypto, max_batch=64, linger_s=0.005,
                                  recorder=rec)
            h = sm3_hash(b"payload")
            sig = crypto.sign(h)
            ok = await fr.verify(sig, h, crypto.pub_key,
                                 msg_type="SignedVote")
            fr.close()
            return ok, rec.tail(), crypto.seen_round_ids

        ok, events, seen = run(main())
        self.assertTrue(ok)
        flushes = [e for e in events if e["kind"] == "round_flush"]
        self.assertEqual(len(flushes), 1)
        flush = flushes[0]
        self.assertEqual(flush["batch"], 1)
        self.assertGreaterEqual(flush["queue_wait_s"], 0.0)
        # the provider saw the SAME id the flush event carries
        self.assertEqual(seen, [flush["round_id"]])

    def test_successive_flushes_get_increasing_ids(self):
        async def main():
            crypto = TaggedCrypto(0xBEEF)
            rec = FlightRecorder(64)
            fr = BatchingVerifier(crypto, max_batch=1, linger_s=0.001,
                                  recorder=rec)
            h = sm3_hash(b"p")
            sig = crypto.sign(h)
            for _ in range(3):
                await fr.verify(sig, h, crypto.pub_key,
                                msg_type="SignedVote")
            fr.close()
            return [e["round_id"] for e in rec.tail()
                    if e["kind"] == "round_flush"]

        ids = run(main())
        self.assertEqual(len(ids), 3)
        self.assertEqual(ids, sorted(ids))
        self.assertEqual(len(set(ids)), 3)


# ---------------------------------------------------------------------------
# straggler detector (unit)
# ---------------------------------------------------------------------------

class StragglerUnit(unittest.TestCase):
    def test_flags_outlier_device(self):
        m = Metrics()
        rec = FlightRecorder(32)
        det = StragglerDetector(metrics=m, recorder=rec, ratio=1.5,
                                min_samples=3)
        flagged = []
        for _ in range(3):
            for dev in ("cpu:0", "cpu:1", "cpu:2"):
                det.observe(dev, "readback", 0.001)
            flagged.append(det.observe("cpu:3", "readback", 0.010))
        self.assertTrue(flagged[-1])  # enough history by the 3rd round
        self.assertEqual(det.flagged_devices(), ["cpu:3"])
        self.assertGreaterEqual(det.flag_count("cpu:3"), 1)
        self.assertEqual(det.flag_count("cpu:0"), 0)
        s = snapshot(m.registry)
        key = "mesh_straggler_total{device=cpu:3,stage=readback}"
        self.assertGreaterEqual(s[key], 1)
        events = [e for e in rec.tail() if e["kind"] == "straggler"]
        self.assertTrue(events)
        self.assertEqual(events[-1]["device"], "cpu:3")
        self.assertGreater(events[-1]["skew"], 1.5)

    def test_needs_min_samples_and_two_devices(self):
        det = StragglerDetector(min_samples=3)
        # one device alone can never be a straggler
        for _ in range(10):
            self.assertFalse(det.observe("cpu:0", "readback", 0.01))
        # a second device below min_samples doesn't flag either
        self.assertFalse(det.observe("cpu:1", "readback", 1.0))
        self.assertEqual(det.flagged_devices(), [])

    def test_statusz_shape(self):
        det = StragglerDetector(ratio=2.0, min_samples=2)
        for _ in range(2):
            det.observe("cpu:0", "readback", 0.001)
            det.observe("cpu:1", "readback", 0.009)
        doc = det.statusz()
        self.assertEqual(doc["ratio"], 2.0)
        devs = doc["stages"]["readback"]["devices"]
        self.assertEqual(set(devs), {"cpu:0", "cpu:1"})
        self.assertEqual(devs["cpu:1"]["samples"], 2)
        self.assertIsNotNone(doc["stages"]["readback"]["mesh_median_s"])
        self.assertIn("flags", doc)
        self.assertIn("flagged_devices", doc)
        self.assertTrue(json.dumps(doc))  # JSON-encodable

    def test_observe_never_raises(self):
        det = StragglerDetector()
        self.assertFalse(det.observe(object(), None, "nan"))


# ---------------------------------------------------------------------------
# straggler detection on the 8-lane virtual CPU mesh
# ---------------------------------------------------------------------------

class StragglerOnVirtualMesh(unittest.TestCase):
    def test_injected_sleep_flags_exactly_that_device(self):
        """The production injection path end to end: a sharded array's
        per-shard fetches (TpuBlsCrypto._shard_latencies) feed
        DeviceProfiler.device_stage, the injected sleep sits inside
        cpu:3's timed window, and the detector flags exactly cpu:3 —
        counter, flightrec event, and /statusz "mesh" all agree."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
        from consensus_overlord_tpu.parallel import make_mesh

        if len(jax.devices()) < 8:  # pragma: no cover — conftest forces 8
            self.skipTest("needs the 8-device virtual mesh")
        m = Metrics()
        rec = FlightRecorder(64)
        prof = DeviceProfiler(m)
        det = StragglerDetector(metrics=m, recorder=rec, ratio=1.5,
                                min_samples=3)
        prof.attach_straggler(det)
        provider = TpuBlsCrypto(0xA11CE)
        provider.bind_profiler(prof)
        # 50 ms: wide enough that background load on a busy CI host
        # can't drag the healthy lanes' fetches over ratio*median
        provider.inject_straggler("cpu:3", 0.05)

        mesh = make_mesh(8)
        arr = jax.device_put(
            np.arange(8, dtype=np.int32),
            NamedSharding(mesh, PartitionSpec("lanes")))
        with tag_round(99):
            for _ in range(3):
                provider._shard_latencies(arr, sampled=True,
                                          stage="readback")

        self.assertEqual(det.flagged_devices(), ["cpu:3"])
        s = snapshot(m.registry)
        key = "mesh_straggler_total{device=cpu:3,stage=readback}"
        self.assertGreaterEqual(s[key], 1)
        # all 8 lanes got per-device stage rows
        rows = prof.device_stage_totals()
        devs = {k.split("/", 1)[0] for k in rows}
        self.assertEqual(devs, {f"cpu:{i}" for i in range(8)})
        events = [e for e in rec.tail() if e["kind"] == "straggler"]
        self.assertTrue(events)
        self.assertEqual(events[-1]["device"], "cpu:3")
        self.assertEqual(events[-1]["round_id"], 99)
        mesh_doc = det.statusz()
        self.assertEqual(mesh_doc["flagged_devices"], ["cpu:3"])
        self.assertGreater(
            mesh_doc["stages"]["readback"]["devices"]["cpu:3"]["skew"],
            1.5)
        # clearing the injection stops the sleep (seconds <= 0 clears)
        provider.inject_straggler("cpu:3", 0)
        self.assertEqual(provider._inject_straggler, {})


# ---------------------------------------------------------------------------
# fleet aggregator
# ---------------------------------------------------------------------------

TREND_DOC = {
    "samples": 5, "span_s": 10.0, "rss_delta_bytes": 1024,
    "rss_slope_bytes_per_s": 102.4, "wal_delta_bytes": 0,
    "wal_growth_bytes_per_s": 0.0, "flightrec_drop_per_s": 0.1,
    "telemetry_jsonl_bytes": 2048,
    "last": {"rss_bytes": 100_000_000, "wal_bytes": 4096,
             "occupancy": 0.875, "uptime_s": 12.0},
}


class FleetAggregatorTests(unittest.TestCase):
    def test_degenerate_single_process_mode(self):
        agg = FleetAggregator("local", lambda: dict(TREND_DOC))
        doc = agg.statusz()
        self.assertTrue(doc["degenerate"])
        self.assertEqual(doc["hosts"], 1)
        self.assertEqual(doc["errors"], [])
        row = doc["rows"]["local"]
        self.assertEqual(row["rss_bytes"], 100_000_000)
        self.assertEqual(row["occupancy"], 0.875)
        self.assertEqual(row["telemetry_jsonl_bytes"], 2048)
        # one host = no skew to report
        self.assertEqual(doc["max_skew"], {})
        self.assertTrue(json.dumps(doc))

    def test_peer_merge_over_loopback_http(self):
        """Host 0 pulls a real peer /statusz over the metrics exporter
        and merges the trend into per-host rows + max-skew."""
        peer_metrics = Metrics()
        peer_trend = dict(TREND_DOC)
        peer_trend["last"] = dict(TREND_DOC["last"],
                                  rss_bytes=160_000_000)
        peer_metrics.add_status_source("trend", lambda: peer_trend)
        port = peer_metrics.start_exporter(0, addr="127.0.0.1")
        try:
            agg = FleetAggregator("host0", lambda: dict(TREND_DOC),
                                  peers=[f"127.0.0.1:{port}"])
            doc = agg.statusz()
        finally:
            peer_metrics.stop_exporter()
        self.assertFalse(doc["degenerate"])
        self.assertEqual(doc["hosts"], 2)
        self.assertEqual(doc["errors"], [])
        peer_row = doc["rows"][f"127.0.0.1:{port}"]
        self.assertEqual(peer_row["rss_bytes"], 160_000_000)
        skew = doc["max_skew"]["rss_bytes"]
        self.assertEqual(skew["abs_skew"], 30_000_000)

    def test_dead_peer_degrades_to_error_row(self):
        agg = FleetAggregator("host0", lambda: dict(TREND_DOC),
                              peers=["127.0.0.1:1"], timeout_s=0.2)
        doc = agg.statusz()
        self.assertEqual(doc["errors"], ["127.0.0.1:1"])
        self.assertIn("error", doc["rows"]["127.0.0.1:1"])
        # the local row still renders — a sick peer must not blank the
        # fleet section
        self.assertIn("rss_bytes", doc["rows"]["host0"])


# ---------------------------------------------------------------------------
# telemetry: startup rotation, jsonl size, observer hook
# ---------------------------------------------------------------------------

class TelemetryRotation(unittest.TestCase):
    def test_oversized_preexisting_file_rotates_at_startup(self):
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "soak.jsonl")
            with open(path, "w") as f:
                for i in range(10):
                    f.write(json.dumps({"seq": i}) + "\n")
            sampler = TelemetrySampler(interval_s=60, out_path=path,
                                       window=4, max_file_samples=10)
            with open(path) as f:
                lines = f.readlines()
            # rewritten down to the retained window, newest last
            self.assertEqual(len(lines), 4)
            self.assertEqual(json.loads(lines[-1])["seq"], 9)
            self.assertEqual(sampler._written, 4)

    def test_undersized_file_counts_into_the_bound(self):
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "soak.jsonl")
            with open(path, "w") as f:
                for i in range(3):
                    f.write(json.dumps({"seq": i}) + "\n")
            sampler = TelemetrySampler(interval_s=60, out_path=path,
                                       window=4, max_file_samples=5)
            self.assertEqual(sampler._written, 3)
            # two more appends hit the bound and trigger the rewrite
            sampler.sample_now()
            sampler.sample_now()
            sampler.sample_now()
            with open(path) as f:
                lines = f.readlines()
            self.assertLessEqual(len(lines), 4)

    def test_sample_carries_jsonl_size_and_trend_surfaces_it(self):
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "soak.jsonl")
            sampler = TelemetrySampler(interval_s=60, out_path=path)
            sampler.sample_now()
            doc = sampler.sample_now()  # file exists by the 2nd sample
            self.assertGreater(doc["telemetry_jsonl_bytes"], 0)
            trend = sampler.trend()
            self.assertGreater(trend["telemetry_jsonl_bytes"], 0)

    def test_observer_hook_sees_samples_and_never_breaks(self):
        seen = []

        def bad_observer(doc):
            raise RuntimeError("observer bug")

        sampler = TelemetrySampler(interval_s=60)
        sampler.add_observer(bad_observer).add_observer(seen.append)
        doc = sampler.sample_now()
        self.assertEqual(len(seen), 1)
        self.assertEqual(seen[0]["seq"], doc["seq"])


class StageMeansSeries(unittest.TestCase):
    def test_stage_means_difference_profiler_totals(self):
        """stage_means_s is the per-sample mean over the calls since the
        LAST sample — the stage_time_spike detector's input series."""
        class StubProfiler:
            def __init__(self):
                self.totals = {}

            def stage_totals(self):
                return self.totals

        prof = StubProfiler()
        sampler = TelemetrySampler(interval_s=60, profiler=prof)
        d1 = sampler.sample_now()
        self.assertNotIn("stage_means_s", d1)  # no calls yet
        prof.totals = {"verify_batch/dispatch":
                       {"count": 4, "total_s": 0.8}}
        d2 = sampler.sample_now()
        self.assertEqual(d2["stage_means_s"]["verify_batch/dispatch"],
                         0.2)
        # no new calls -> no series entry (a stale mean would flatline
        # the EWMA baseline)
        d3 = sampler.sample_now()
        self.assertNotIn("stage_means_s", d3)


# ---------------------------------------------------------------------------
# anomaly detectors
# ---------------------------------------------------------------------------

class EwmaSeriesTests(unittest.TestCase):
    def test_warmup_then_z(self):
        s = EwmaSeries(alpha=0.3, min_samples=3)
        self.assertIsNone(s.update(1.0))
        self.assertIsNone(s.update(1.1))
        self.assertIsNone(s.update(0.9))
        z = s.update(10.0)
        self.assertIsNotNone(z)
        self.assertGreater(z, 4.0)

    def test_flat_baseline_departure_is_infinite(self):
        s = EwmaSeries(min_samples=2)
        s.update(1.0)
        s.update(1.0)
        self.assertEqual(s.update(1.0), 0.0)
        self.assertEqual(s.update(2.0), float("inf"))
        s2 = EwmaSeries(min_samples=2)
        s2.update(1.0)
        s2.update(1.0)
        self.assertEqual(s2.update(0.5), float("-inf"))


class AnomalyDetectorTests(unittest.TestCase):
    def _detector(self, **kw):
        m = Metrics()
        rec = FlightRecorder(64)
        det = AnomalyDetector(metrics=m, recorder=rec, **kw)
        return det, m, rec

    def test_occupancy_collapse(self):
        det, m, rec = self._detector(min_samples=3)
        for _ in range(5):
            det.observe_sample({"occupancy": 0.9})
        det.observe_sample({"occupancy": 0.05})
        self.assertEqual(det.alert_count("occupancy_collapse"), 1)
        s = snapshot(m.registry)
        self.assertEqual(
            s["obs_alerts_total{kind=occupancy_collapse}"], 1)
        alerts = [e for e in rec.tail() if e["kind"] == "alert"]
        self.assertEqual(alerts[-1]["occupancy"], 0.05)
        # a HIGH occupancy departure is never an incident
        det2, _, _ = self._detector(min_samples=3)
        for _ in range(5):
            det2.observe_sample({"occupancy": 0.5})
        det2.observe_sample({"occupancy": 1.0})
        self.assertEqual(det2.alert_count(), 0)

    def test_stage_time_spike(self):
        det, _, rec = self._detector(min_samples=3)
        for _ in range(5):
            det.observe_sample(
                {"stage_means_s": {"verify_batch/dispatch": 0.01}})
        det.observe_sample(
            {"stage_means_s": {"verify_batch/dispatch": 5.0}})
        self.assertEqual(det.alert_count("stage_time_spike"), 1)
        alerts = [e for e in rec.tail() if e["kind"] == "alert"]
        self.assertEqual(alerts[-1]["stage"], "verify_batch/dispatch")

    def test_shed_storm(self):
        det, _, _ = self._detector(min_samples=3)
        for total in (0, 0, 0, 0, 0, 0):
            det.observe_sample(
                {"counters": {"frontier_admission_sheds_total": total}})
        det.observe_sample(
            {"counters": {"frontier_admission_sheds_total": 500}})
        self.assertEqual(det.alert_count("shed_storm"), 1)

    def test_straggler_persistence(self):
        class StubStraggler:
            def __init__(self):
                self.flags = 0

            def flag_count(self):
                return self.flags

            def flagged_devices(self):
                return ["cpu:3"]

        stub = StubStraggler()
        det = AnomalyDetector(straggler=stub, straggler_window=5,
                              straggler_min_flagged=3)
        for _ in range(2):  # two flagged samples: below the bar
            stub.flags += 1
            det.observe_sample({})
        self.assertEqual(det.alert_count("straggler_persistence"), 0)
        stub.flags += 1
        det.observe_sample({})  # third flagged sample in the window
        self.assertEqual(det.alert_count("straggler_persistence"), 1)
        alerts = det.tail()
        self.assertEqual(alerts[-1]["devices"], ["cpu:3"])
        # the window cleared: persistence must re-accumulate
        stub.flags += 1
        det.observe_sample({})
        self.assertEqual(det.alert_count("straggler_persistence"), 1)

    def test_statusz_and_synthetic_alerts(self):
        det, m, rec = self._detector()
        for i in range(3):
            det.raise_alert("synthetic_storm", index=i)
        doc = det.statusz(tail=2)
        self.assertEqual(doc["total"], 3)
        self.assertEqual(doc["by_kind"], {"synthetic_storm": 3})
        self.assertEqual(len(doc["recent"]), 2)
        self.assertEqual(det.alert_count(), 3)
        s = snapshot(m.registry)
        self.assertEqual(s["obs_alerts_total{kind=synthetic_storm}"], 3)
        self.assertEqual(
            len([e for e in rec.tail() if e["kind"] == "alert"]), 3)
        self.assertTrue(json.dumps(doc))

    def test_observe_sample_never_raises(self):
        det, _, _ = self._detector()
        det.observe_sample({"occupancy": "not-a-number",
                            "stage_means_s": "nope",
                            "counters": None})
        det.observe_sample(None)  # type: ignore[arg-type]
        self.assertEqual(det.alert_count(), 0)


# ---------------------------------------------------------------------------
# waterfall reconstruction
# ---------------------------------------------------------------------------

SUMMARY_FIXTURE = {
    "profile": {
        "recent": [
            {"seq": 1, "ts": 100.0, "op": "verify_batch", "batch": 8,
             "ok": True, "round_id": 1,
             "stages_s": {"parse": 0.001, "dispatch": 0.004,
                          "readback": 0.002, "pairing": 0.003},
             "stages_at_s": {"parse": 0.001, "dispatch": 0.005,
                             "readback": 0.007, "pairing": 0.010}},
            {"seq": 2, "ts": 101.0, "op": "verify_batch", "batch": 8,
             "ok": True, "round_id": 2,
             "stages_s": {"parse": 0.001, "dispatch": 0.004},
             "stages_at_s": {"parse": 0.001, "dispatch": 0.005}},
            {"seq": 3, "ts": 102.0, "op": "aggregate", "batch": 4,
             "ok": True, "round_id": 3,
             "stages_s": {"parse": 0.002, "dispatch": 0.006}},
        ],
    },
    "flightrec": [
        {"seq": 1, "ts": 100.0, "kind": "round_flush", "round_id": 1,
         "batch": 8, "queue_wait_s": 0.002},
        {"seq": 2, "ts": 100.5, "kind": "straggler", "round_id": 1,
         "device": "cpu:3", "stage": "readback", "skew": 2.1},
        {"seq": 3, "ts": 103.0, "kind": "alert", "round_id": 3,
         "alert_kind": "stage_time_spike"},
    ],
}


class WaterfallScript(unittest.TestCase):
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, WATERFALL, *argv],
            capture_output=True, text=True, timeout=60)

    def test_reconstructs_rounds_with_ring_ordering(self):
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "summary.json")
            with open(path, "w") as f:
                json.dump(SUMMARY_FIXTURE, f)
            proc = self._run(path, "--json")
            self.assertEqual(proc.returncode, 0, proc.stderr)
            doc = json.loads(proc.stdout)
        self.assertEqual(doc["count"], 3)
        r1 = doc["rounds"][0]
        self.assertEqual(r1["round_id"], 1)
        # queue wait leads (negative offset anchors flush at 0), then
        # the ring's stage order: parse -> dispatch -> readback ->
        # pairing, exactly the stages_at_s sequence
        names = [s["stage"] for s in r1["segments"]]
        self.assertEqual(names, ["queue_wait", "parse", "dispatch",
                                 "readback", "pairing"])
        starts = [s["start_s"] for s in r1["segments"]]
        self.assertEqual(starts, sorted(starts))
        # annotations ride their round
        self.assertEqual(r1["annotations"][0]["device"], "cpu:3")
        self.assertEqual(doc["rounds"][2]["annotations"][0]["kind"],
                         "alert")
        # legacy record without stages_at_s still orders by stage rank
        r3 = doc["rounds"][2]
        seg_names = [s["stage"] for s in r3["segments"]]
        self.assertEqual(seg_names, ["parse", "dispatch"])

    def test_text_rendering_and_empty_input_exit_codes(self):
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "summary.json")
            with open(path, "w") as f:
                json.dump(SUMMARY_FIXTURE, f)
            proc = self._run(path)
            self.assertEqual(proc.returncode, 0, proc.stderr)
            self.assertIn("round 1", proc.stdout)
            self.assertIn("queue_wait", proc.stdout)
            self.assertIn("rounds: 3", proc.stdout)
            empty = os.path.join(td, "empty.json")
            with open(empty, "w") as f:
                json.dump({"profile": {"recent": []}}, f)
            proc2 = self._run(empty)
            self.assertEqual(proc2.returncode, 4)


if __name__ == "__main__":
    unittest.main()
