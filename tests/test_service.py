"""Service-process integration: the real gRPC microservice booted against
in-process fake network/controller siblings (reference deployment shape,
SURVEY.md §4) — registration retry, ping_controller bootstrap, NetworkMsg
push delivery, commits end-to-end, proof audit over RPC, NotReady gate,
module guard, health, metrics."""

import asyncio
import json
import tempfile
import unittest
import urllib.request

import grpc

from consensus_overlord_tpu.crypto.provider import CpuBlsCrypto
from consensus_overlord_tpu.service.config import ConsensusConfig
from consensus_overlord_tpu.service.main import ServiceRuntime
from consensus_overlord_tpu.service.pb import pb2
from consensus_overlord_tpu.service.rpc import (
    CONSENSUS_SERVICE,
    HEALTH_SERVICE,
    NETWORK_MSG_HANDLER_SERVICE,
    Code,
    RetryClient,
)
from consensus_overlord_tpu.sim.grpc_fakes import (
    FakeController,
    NetworkFabric,
    start_fake_controller,
    start_fake_network,
)

N_NODES = 4
KEYS = [0x5EED + 31 * i for i in range(N_NODES)]


class ServiceEndToEnd(unittest.TestCase):
    def test_four_node_grpc_consensus(self):
        """Four ServiceRuntimes + four fake network siblings + one fake
        controller commit blocks over real gRPC, then the committed proof
        passes CheckBlock and a tampered one fails."""

        async def main():
            cryptos = [CpuBlsCrypto(k) for k in KEYS]
            validators = [c.pub_key for c in cryptos]
            fabric = NetworkFabric()
            fabric.set_validators(validators)
            # interval 2 s: round timers scale off it, and pure-Python BLS
            # on the 1-core CI box needs the headroom to beat the timeouts
            controller = FakeController(validators, block_interval=2)
            ctrl_server, ctrl_port = await start_fake_controller(controller)
            net_servers = []
            runtimes = []
            tmp = tempfile.TemporaryDirectory()
            try:
                for i in range(N_NODES):
                    net_server, net_port = await start_fake_network(fabric, i)
                    net_servers.append(net_server)
                    config = ConsensusConfig(
                        network_port=net_port,
                        consensus_port=0,           # OS-assigned
                        controller_port=ctrl_port,
                        server_retry_interval=1,
                        wal_path=f"{tmp.name}/wal{i}",
                        enable_metrics=(i == 0),
                        metrics_port=0,
                        crypto_backend="cpu")
                    rt = ServiceRuntime(config, KEYS[i], host="localhost")
                    port = await rt.start()
                    controller.consensus_addrs.append(f"localhost:{port}")
                    runtimes.append(rt)

                await controller.wait_for_height(2, timeout=120)

                # -- proof audit over RPC (reference src/main.rs:107-127) --
                h = 1
                client = RetryClient(
                    f"localhost:{runtimes[0].bound_port}",
                    "ConsensusService", CONSENSUS_SERVICE, retries=1)
                good = pb2.ProposalWithProof(
                    proposal=pb2.Proposal(height=h, data=controller.chain[h]),
                    proof=controller.proofs[h])
                resp = await client.call("CheckBlock", good)
                self.assertEqual(resp.code, Code.SUCCESS)
                bad = pb2.ProposalWithProof(
                    proposal=pb2.Proposal(height=h,
                                          data=controller.chain[h] + b"x"),
                    proof=controller.proofs[h])
                resp = await client.call("CheckBlock", bad)
                self.assertEqual(resp.code, Code.PROPOSAL_CHECK_ERROR)
                await client.close()

                # -- metrics exporter serves the RPC histogram -------------
                port = runtimes[0].metrics_port
                self.assertIsNotNone(port)
                body = await asyncio.to_thread(
                    lambda: urllib.request.urlopen(
                        f"http://localhost:{port}/metrics", timeout=5).read())
                self.assertIn(b"grpc_server_handling_ms", body)
                self.assertIn(b"ProcessNetworkMsg", body)
                # hot-path families exported with real observations
                self.assertIn(b"frontier_batch_size_count", body)
                self.assertIn(b"wal_append_ms_count", body)

                # -- /statusz: live height/round + flight-recorder tail ----
                status = json.loads(await asyncio.to_thread(
                    lambda: urllib.request.urlopen(
                        f"http://localhost:{port}/statusz", timeout=5).read()))
                self.assertGreaterEqual(status["consensus"]["height"], 1)
                self.assertIn("round", status["consensus"])
                self.assertIn("leader", status["consensus"])
                self.assertGreaterEqual(status["frontier"]["batches"], 0)
                kinds = [e["kind"] for e in status["flightrec"]]
                self.assertIn("enter_round", kinds)

                # every node's frontier actually batched signatures
                stats = [rt.consensus.frontier.stats for rt in runtimes]
                self.assertTrue(any(s.batches > 0 for s in stats))
            finally:
                for rt in runtimes:
                    await rt.stop()
                for s in net_servers:
                    await s.stop(0.5)
                await ctrl_server.stop(0.5)
                await controller.close()
                await fabric.close()
                tmp.cleanup()

        asyncio.run(main())

    def test_not_ready_module_guard_health(self):
        """Before any reconfiguration: CheckBlock → NOT_READY; foreign
        module → INVALID_ARGUMENT; Health → SERVING
        (reference src/main.rs:112-115, 139-142; health_check.rs:29-35)."""

        async def main():
            fabric = NetworkFabric()
            controller = FakeController([], block_interval=1)
            net_server, net_port = await start_fake_network(fabric, 0)
            tmp = tempfile.TemporaryDirectory()
            config = ConsensusConfig(
                network_port=net_port, consensus_port=0,
                controller_port=1,  # nothing listens: stays NotReady
                server_retry_interval=1, wal_path=f"{tmp.name}/wal",
                enable_metrics=False, crypto_backend="cpu")
            rt = ServiceRuntime(config, 0xABCDEF, host="localhost")
            try:
                port = await rt.start()
                addr = f"localhost:{port}"

                cons = RetryClient(addr, "ConsensusService",
                                   CONSENSUS_SERVICE, retries=1)
                resp = await cons.call("CheckBlock", pb2.ProposalWithProof(
                    proposal=pb2.Proposal(height=1, data=b"x"), proof=b""))
                self.assertEqual(resp.code, Code.NOT_READY)
                await cons.close()

                net = RetryClient(addr, "NetworkMsgHandlerService",
                                  NETWORK_MSG_HANDLER_SERVICE, retries=1)
                with self.assertRaises(grpc.aio.AioRpcError) as ctx:
                    await net.call("ProcessNetworkMsg", pb2.NetworkMsg(
                        module="storage", type="SignedVote", msg=b""))
                self.assertEqual(ctx.exception.code(),
                                 grpc.StatusCode.INVALID_ARGUMENT)
                # valid module + garbage payload: logged-and-dropped Success
                resp = await net.call("ProcessNetworkMsg", pb2.NetworkMsg(
                    module="consensus", type="SignedVote", msg=b"\xff\xff"))
                self.assertEqual(resp.code, Code.SUCCESS)
                await net.close()

                health = RetryClient(addr, "Health", HEALTH_SERVICE,
                                     retries=1)
                resp = await health.call(
                    "Check", pb2.HealthCheckRequest(service=""))
                self.assertEqual(resp.status,
                                 pb2.HealthCheckResponse.SERVING)
                await health.close()
            finally:
                await rt.stop()
                await net_server.stop(0.5)
                await controller.close()
                await fabric.close()
                tmp.cleanup()

        asyncio.run(main())


class ProtoCompat(unittest.TestCase):
    def test_cita_cloud_method_paths_round_trip(self):
        """proto_compat='cita_cloud' (VERDICT r3 item 8): the served and
        dialed gRPC method paths become the reference mesh's
        cita_cloud_proto names (reference src/main.rs:64-73) —
        /consensus.ConsensusService/..., /network..., /controller...,
        /grpc.health.v1.Health/Check — and a compat-mode client round-
        trips against a compat-mode handler.  Native mode is restored
        for the rest of the suite."""
        from consensus_overlord_tpu.service.rpc import (
            full_service_name, generic_handler, set_proto_compat)

        async def main():
            set_proto_compat("cita_cloud")
            try:
                self.assertEqual(full_service_name("ConsensusService"),
                                 "consensus.ConsensusService")
                self.assertEqual(full_service_name("NetworkService"),
                                 "network.NetworkService")
                self.assertEqual(
                    full_service_name("Consensus2ControllerService"),
                    "controller.Consensus2ControllerService")
                self.assertEqual(full_service_name("Health"),
                                 "grpc.health.v1.Health")

                class _Health:
                    async def check(self, request, context):
                        return pb2.HealthCheckResponse(
                            status=pb2.HealthCheckResponse.SERVING)

                server = grpc.aio.server()
                server.add_generic_rpc_handlers(
                    (generic_handler("Health", HEALTH_SERVICE, _Health()),))
                port = server.add_insecure_port("127.0.0.1:0")
                await server.start()
                try:
                    # compat-mode RetryClient dials the cita_cloud path
                    client = RetryClient(f"127.0.0.1:{port}", "Health",
                                         HEALTH_SERVICE, retries=1)
                    resp = await client.call(
                        "Check", pb2.HealthCheckRequest(service=""))
                    self.assertEqual(resp.status,
                                     pb2.HealthCheckResponse.SERVING)
                    await client.close()

                    # a RAW channel proves the wire path literally
                    chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
                    call = chan.unary_unary(
                        "/grpc.health.v1.Health/Check",
                        request_serializer=(
                            pb2.HealthCheckRequest.SerializeToString),
                        response_deserializer=(
                            pb2.HealthCheckResponse.FromString))
                    resp = await call(pb2.HealthCheckRequest(service=""),
                                      timeout=5.0)
                    self.assertEqual(resp.status,
                                     pb2.HealthCheckResponse.SERVING)
                    await chan.close()

                    # native-mode path must NOT be served in compat mode
                    chan = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
                    bad = chan.unary_unary(
                        "/consensus_overlord_tpu.Health/Check",
                        request_serializer=(
                            pb2.HealthCheckRequest.SerializeToString),
                        response_deserializer=(
                            pb2.HealthCheckResponse.FromString))
                    with self.assertRaises(grpc.aio.AioRpcError) as ctx:
                        await bad(pb2.HealthCheckRequest(service=""),
                                  timeout=5.0)
                    self.assertEqual(ctx.exception.code(),
                                     grpc.StatusCode.UNIMPLEMENTED)
                    await chan.close()
                finally:
                    await server.stop(0.2)
            finally:
                set_proto_compat("native")

        asyncio.run(main())


if __name__ == "__main__":
    unittest.main()
