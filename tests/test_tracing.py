"""Trace export + propagation: agent_endpoint spans on the UDP wire and
traceparent injection on outbound gRPC calls (VERDICT r2 items 4/8)."""

import asyncio
import socket

import grpc

from consensus_overlord_tpu.obs import (
    JaegerExporter, Span, TraceContextInterceptor, span_context,
    trace_context)
from consensus_overlord_tpu.obs.tracing import encode_batch
from consensus_overlord_tpu.service.rpc import (
    HEALTH_SERVICE, RetryClient, generic_handler)
from consensus_overlord_tpu.service.pb import pb2

TRACE_ID = "0123456789abcdef0123456789abcdef"


def udp_listener():
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(5.0)
    return sock, sock.getsockname()[1]


class TestEncoding:
    def test_batch_message_shape(self):
        sp = Span(trace_id=int(TRACE_ID, 16), span_id=0x1122334455667788,
                  parent_span_id=0, operation="/pkg.Svc/Method",
                  start_us=1_000_000, duration_us=500)
        data = encode_batch("consensus", [sp])
        assert data[0] == 0x82          # compact protocol id
        assert data[1] == 0x21          # version 1 | CALL << 5
        assert b"emitBatch" in data
        assert b"consensus" in data
        assert b"/pkg.Svc/Method" in data


class TestExporterWire:
    def test_span_reaches_agent_socket(self):
        sock, port = udp_listener()
        exporter = JaegerExporter(f"127.0.0.1:{port}", "svc-under-test",
                                  linger_s=0.05)
        try:
            exporter.report(Span(
                trace_id=int(TRACE_ID, 16), span_id=0xABCDEF12,
                parent_span_id=0x42, operation="op-name",
                start_us=123, duration_us=456))
            data, _ = sock.recvfrom(65536)
        finally:
            exporter.close()
            sock.close()
        assert b"svc-under-test" in data
        assert b"op-name" in data


class _Health:
    """Health service impl that records its request-time trace context
    and makes one OUTBOUND call so injection can be asserted."""

    def __init__(self):
        self.seen_trace = None
        self.client = None

    async def check(self, request, context):
        self.seen_trace = trace_context.get()
        assert span_context.get()  # a server span id is active
        if self.client is not None:
            await self.client.call("Check", pb2.HealthCheckRequest())
        return pb2.HealthCheckResponse(status=1)


class _Echo:
    """Downstream service recording inbound metadata."""

    def __init__(self):
        self.metadata = None

    async def check(self, request, context):
        self.metadata = dict(context.invocation_metadata() or ())
        return pb2.HealthCheckResponse(status=1)


async def _serve(impl, interceptors=()):
    server = grpc.aio.server(interceptors=list(interceptors))
    server.add_generic_rpc_handlers(
        (generic_handler("Health", HEALTH_SERVICE, impl),))
    port = server.add_insecure_port("127.0.0.1:0")
    await server.start()
    return server, port


class TestEngineSpans:
    def test_consensus_lifecycle_spans_on_wire(self):
        """The engine's own spans (VERDICT r3 item 7): running a
        1-validator net with a tracer must ship consensus.height /
        consensus.round / consensus.qc_verify spans to the agent socket —
        the round lifecycle the reference #[instrument]s
        (src/consensus.rs:96,143,209)."""

        async def main():
            from consensus_overlord_tpu.sim import SimNetwork

            sock, udp_port = udp_listener()
            exporter = JaegerExporter(f"127.0.0.1:{udp_port}", "consensus",
                                      linger_s=0.02)
            net = SimNetwork(n_validators=4, block_interval_ms=20)
            for node in net.nodes:
                node.engine.tracer = exporter
            net.start(init_height=1)
            await net.run_until_height(3)
            await net.stop()
            exporter.close()

            loop = asyncio.get_running_loop()
            seen = b""
            for _ in range(16):
                try:
                    data, _ = await loop.run_in_executor(
                        None, lambda: sock.recvfrom(65536))
                except socket.timeout:
                    break
                seen += data
                if (b"consensus.height" in seen
                        and b"consensus.round" in seen
                        and b"consensus.qc_verify" in seen):
                    break
            sock.close()
            assert b"consensus.round" in seen
            assert b"consensus.height" in seen
            assert b"consensus.qc_verify" in seen

        asyncio.run(main())


class TestPropagation:
    def test_trace_spans_and_outbound_injection(self):
        """inbound traceparent → server span exported with that trace id
        AND re-injected (with the server's span as parent) on the
        handler's outbound gRPC call — the cross-hop propagation the
        reference does via cloud_util::tracer (src/main.rs:96)."""

        async def main():
            sock, udp_port = udp_listener()
            exporter = JaegerExporter(f"127.0.0.1:{udp_port}", "consensus",
                                      linger_s=0.05)
            echo = _Echo()
            down_server, down_port = await _serve(echo)
            front = _Health()
            front_server, front_port = await _serve(
                front, [TraceContextInterceptor(exporter=exporter)])
            front.client = RetryClient(f"127.0.0.1:{down_port}", "Health",
                                       HEALTH_SERVICE)
            caller = RetryClient(f"127.0.0.1:{front_port}", "Health",
                                 HEALTH_SERVICE)
            try:
                resp = await caller._calls["Check"](
                    pb2.HealthCheckRequest(), timeout=5.0,
                    metadata=(("traceparent",
                               f"00-{TRACE_ID}-00000000000000aa-01"),))
                assert resp.status == 1
                # The handler observed the inbound trace id.
                assert front.seen_trace == TRACE_ID
                # Outbound wire carried traceparent with the same trace
                # id and a NEW span id (the server span, not the
                # caller's).
                tp = echo.metadata.get("traceparent", "")
                assert tp.startswith(f"00-{TRACE_ID}-")
                assert "00000000000000aa" not in tp
                # The exported span datagram names the operation.
                data, _ = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: sock.recvfrom(65536))
                assert b"Check" in data
            finally:
                await caller.close()
                await front.client.close()
                await front_server.stop(0.1)
                await down_server.stop(0.1)
                exporter.close()
                sock.close()

        asyncio.run(main())
