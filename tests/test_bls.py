"""BLS12-381 CPU oracle tests: field tower, pairing laws, serialization,
signature scheme, and the provider port."""

import pytest

from consensus_overlord_tpu.crypto import bls12381 as bls
from consensus_overlord_tpu.crypto.provider import (
    CpuBlsCrypto,
    CryptoError,
    Ed25519Crypto,
)

SK1 = 0x263DDE57AE9E9F9E285C96F1DD21BC9B9E91B321ADF6B8A0F8B07ACDA9D8C2B1 % bls.R
SK2 = 0x0A0B0C0D0E0F101112131415161718191A1B1C1D1E1F2021222324252627282A % bls.R


class TestFieldTower:
    def test_fq2_inverse(self):
        a = (123456789, 987654321)
        assert bls.fq2_mul(a, bls.fq2_inv(a)) == bls.FQ2_ONE

    def test_fq6_inverse(self):
        a = ((1, 2), (3, 4), (5, 6))
        assert bls.fq6_mul(a, bls.fq6_inv(a)) == bls.FQ6_ONE

    def test_fq12_inverse_and_pow(self):
        a = bls.fq12_add(bls.fq2_to_fq12((7, 9)), (bls.FQ6_ZERO, bls.FQ6_ONE))
        assert bls.fq12_mul(a, bls.fq12_inv(a)) == bls.FQ12_ONE
        assert bls.fq12_pow(a, 5) == bls.fq12_mul(
            bls.fq12_mul(bls.fq12_mul(a, a), bls.fq12_mul(a, a)), a)

    def test_fq2_sqrt_roundtrip(self):
        a = (31415926, 27182818)
        sq = bls.fq2_sq(a)
        root = bls.fq2_sqrt(sq)
        assert root in (a, bls.fq2_neg(a))


class TestCurve:
    def test_generators_on_curve_and_in_subgroup(self):
        assert bls.g1_in_subgroup(bls.G1_GEN)
        assert bls.g2_in_subgroup(bls.G2_GEN)

    def test_group_law(self):
        p2 = bls.g1_mul(bls.G1_GEN, 2)
        p3 = bls.g1_mul(bls.G1_GEN, 3)
        assert bls.g1_add(p2, bls.G1_GEN) == p3
        assert bls.g1_add(p3, bls.g1_neg(p3)) is None
        q2 = bls.g2_mul(bls.G2_GEN, 2)
        assert bls.g2_add(q2, bls.G2_GEN) == bls.g2_mul(bls.G2_GEN, 3)

    def test_scalar_mul_order(self):
        assert bls.g1_mul(bls.G1_GEN, bls.R) is None
        assert bls.g2_mul(bls.G2_GEN, bls.R) is None


class TestSerialization:
    def test_g1_generator_known_answer(self):
        # Standard compressed G1 generator (ZCash format).
        assert bls.g1_compress(bls.G1_GEN).hex() == (
            "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
            "6c55e83ff97a1aeffb3af00adb22c6bb")

    def test_g2_generator_known_answer(self):
        # Standard compressed G2 generator (ZCash format).
        assert bls.g2_compress(bls.G2_GEN).hex() == (
            "93e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049"
            "334cf11213945d57e5ac7d055d042b7e024aa2b2f08f0a91260805272dc51051"
            "c6e47ad4fa403b02b4510b647ae3d1770bac0326a805bbefd48056c8c121bdb8")

    def test_roundtrip_including_sign_bit(self):
        for k in (1, 2, 5, 1234567):
            p = bls.g1_mul(bls.G1_GEN, k)
            assert bls.g1_decompress(bls.g1_compress(p)) == p
            q = bls.g2_mul(bls.G2_GEN, k)
            assert bls.g2_decompress(bls.g2_compress(q)) == q

    def test_infinity_roundtrip(self):
        assert bls.g1_decompress(bls.g1_compress(None)) is None
        assert bls.g2_decompress(bls.g2_compress(None)) is None

    def test_malformed_rejected(self):
        with pytest.raises(ValueError):
            bls.g1_decompress(b"\x00" * 48)  # not compressed
        with pytest.raises(ValueError):
            bls.g1_decompress(bytes([0x80]) + b"\xff" * 47)  # x >= p
        with pytest.raises(ValueError):
            bls.g1_decompress(b"\x97" * 10)  # wrong length
        # x not on curve: search the first x where x³+4 is a non-residue.
        x = next(x for x in range(1, 100)
                 if bls.fq_sqrt((x * x * x + 4) % bls.P) is None)
        bad = bytearray(x.to_bytes(48, "big"))
        bad[0] |= 0x80
        with pytest.raises(ValueError):
            bls.g1_decompress(bytes(bad))


class TestPairing:
    def test_bilinearity(self):
        e = bls.pairing(bls.G2_GEN, bls.G1_GEN)
        assert e != bls.FQ12_ONE
        assert bls.pairing(bls.G2_GEN, bls.g1_mul(bls.G1_GEN, 2)) == \
            bls.fq12_pow(e, 2)
        assert bls.pairing(bls.g2_mul(bls.G2_GEN, 3), bls.G1_GEN) == \
            bls.fq12_pow(e, 3)

    def test_multi_pairing_cancellation(self):
        # e(P, -Q) * e(P, Q) == 1
        neg_g2 = (bls.G2_GEN[0], bls.fq2_neg(bls.G2_GEN[1]))
        assert bls.multi_pairing_is_one(
            [(bls.G1_GEN, bls.G2_GEN), (bls.G1_GEN, neg_g2)])


class TestSignatureScheme:
    def test_sign_verify(self):
        pk = bls.sk_to_pk(SK1)
        msg = b"\xaa" * 32
        sig = bls.sign(SK1, msg)
        assert len(sig) == 48 and len(pk) == 96
        assert bls.verify(pk, msg, sig)
        assert not bls.verify(pk, b"\xbb" * 32, sig)
        assert not bls.verify(bls.sk_to_pk(SK2), msg, sig)

    def test_deterministic(self):
        assert bls.sign(SK1, b"m") == bls.sign(SK1, b"m")

    def test_domain_separation(self):
        pk = bls.sk_to_pk(SK1)
        sig = bls.sign(SK1, b"m", domain=b"chain-a")
        assert bls.verify(pk, b"m", sig, domain=b"chain-a")
        assert not bls.verify(pk, b"m", sig, domain=b"chain-b")

    def test_aggregate_verify(self):
        sks = [SK1, SK2, (SK1 * 7 + 3) % bls.R]
        pks = [bls.sk_to_pk(s) for s in sks]
        msg = b"\xcd" * 32
        agg = bls.aggregate_signatures([bls.sign(s, msg) for s in sks])
        assert len(agg) == 48
        assert bls.aggregate_verify_same_message(pks, msg, agg)
        assert not bls.aggregate_verify_same_message(pks[:2], msg, agg)
        assert not bls.aggregate_verify_same_message(pks, b"\xce" * 32, agg)

    def test_garbage_signature_rejected_not_raised(self):
        pk = bls.sk_to_pk(SK1)
        assert not bls.verify(pk, b"m", b"\x00" * 48)
        assert not bls.verify(b"\x01" * 96, b"m", bls.sign(SK1, b"m"))


class TestProviders:
    def test_cpu_bls_provider_roundtrip(self):
        a = CpuBlsCrypto(SK1)
        b = CpuBlsCrypto(SK2)
        h = a.hash(b"proposal data")
        assert len(h) == 32
        sig_a, sig_b = a.sign(h), b.sign(h)
        assert b.verify_signature(sig_a, h, a.pub_key)
        assert not b.verify_signature(sig_a, h, b.pub_key)
        agg = a.aggregate_signatures([sig_a, sig_b], [a.pub_key, b.pub_key])
        assert a.verify_aggregated_signature(agg, h, [a.pub_key, b.pub_key])
        assert not a.verify_aggregated_signature(agg, h, [a.pub_key])

    def test_aggregate_length_mismatch(self):
        a = CpuBlsCrypto(SK1)
        with pytest.raises(CryptoError):
            a.aggregate_signatures([b"\x00" * 48], [])

    def test_ed25519_provider(self):
        # This test is ABOUT Ed25519Crypto, so the sim_crypto fallback
        # would defeat it: skip where the optional backend is absent.
        pytest.importorskip("cryptography")
        a = Ed25519Crypto(b"\x01" * 32)
        b = Ed25519Crypto(b"\x02" * 32)
        h = a.hash(b"vote")
        sig_a, sig_b = a.sign(h), b.sign(h)
        assert b.verify_signature(sig_a, h, a.pub_key)
        assert not a.verify_signature(sig_a, h, b.pub_key)
        agg = a.aggregate_signatures([sig_a, sig_b], [a.pub_key, b.pub_key])
        assert a.verify_aggregated_signature(agg, h, [a.pub_key, b.pub_key])
        assert not a.verify_aggregated_signature(agg, h, [b.pub_key, a.pub_key])
