"""Byzantine-fault tests: adversarial messages injected at the engine
boundary must never move the state machine.

Each test targets a specific engine guard (VERDICT r1 §weak-5):
  forged QC signature / tampered voter bitmap / sub-quorum bitmap
      → Engine._verify_qc (engine/smr.py)
  equivocating leader, non-leader proposal, bad proposal signature
      → Engine._on_signed_proposal
  duplicate-vote replay, forged vote signature, non-validator voter
      → Engine._on_signed_vote
plus randomized adversarial message schedules over the sim asserting the
chain-level fork invariant (SimController raises SafetyViolation on any
two distinct blocks at one height)."""

import asyncio
import unittest

from consensus_overlord_tpu.core.bitmap import build_bitmap, extract_voters
from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.core.types import (
    AggregatedSignature,
    AggregatedVote,
    Hash,
    Node,
    Proposal,
    SignedProposal,
    SignedVote,
    Vote,
    VoteType,
)
from consensus_overlord_tpu.crypto.provider import sim_crypto
from consensus_overlord_tpu.engine.smr import Engine
from consensus_overlord_tpu.engine.wal import MemoryWal
from consensus_overlord_tpu.sim.harness import SimNetwork


def make_cryptos(n=4):
    return [sim_crypto(i.to_bytes(4, "big") * 8) for i in range(n)]


class StubAdapter:
    """Records every outbound action; commit always 'fails' (returns None)
    so the engine stays at the height under test."""

    def __init__(self, content=b"block content"):
        self.content = content
        self.block_hash = sm3_hash(content)
        self.commits = []
        self.broadcasts = []
        self.transmits = []

    async def get_block(self, height: int):
        return self.content, self.block_hash

    async def check_block(self, height: int, block_hash: Hash,
                          content: bytes) -> bool:
        return True

    async def commit(self, height: int, commit):
        self.commits.append((height, commit))
        return None

    async def get_authority_list(self, height: int):
        return []

    async def broadcast_to_other(self, msg_type: str, payload: bytes):
        self.broadcasts.append((msg_type, payload))

    async def transmit_to_relayer(self, relayer, msg_type: str,
                                  payload: bytes):
        self.transmits.append((bytes(relayer), msg_type, payload))

    def report_error(self, context: str) -> None:
        pass

    def report_view_change(self, height, round, reason) -> None:
        pass


class EngineHarness:
    """One engine under test (validator 0 of 4), driven by hand-crafted
    messages signed with the other validators' real keys."""

    def __init__(self):
        # The engine under test is the validator with the SMALLEST address
        # (sorted-authority slot 0), making leadership deterministic:
        # leader(h, 0) = sorted_slot[h % 4], so the engine follows at
        # heights 1–3 and leads at height 4.
        cryptos = make_cryptos(4)
        cryptos.sort(key=lambda c: c.pub_key)
        self.cryptos = cryptos
        self.by_addr = {c.pub_key: c for c in self.cryptos}
        self.nodes = [Node(c.pub_key) for c in self.cryptos]
        self.adapter = StubAdapter()
        self.engine = Engine(self.cryptos[0].pub_key, self.adapter,
                             self.cryptos[0], MemoryWal())

    async def start(self, height=1):
        self._task = asyncio.get_running_loop().create_task(
            self.engine.run(height, 60_000, self.nodes))
        await asyncio.sleep(0.05)  # let the engine enter the round

    async def settle(self, s=0.1):
        await asyncio.sleep(s)

    async def stop(self):
        self.engine.stop()
        await asyncio.wait_for(self._task, 5)

    # -- crafted messages ---------------------------------------------------

    def leader(self, height, round_=0):
        return self.engine.leader(height, round_)

    def leader_height(self):
        """A height whose round-0 leader IS the engine under test."""
        for height in range(1, 6):
            if self.leader(height) == self.engine.name:
                return height
        raise AssertionError("validator 0 never leads")

    def non_leader_height(self):
        """A height whose round-0 leader is NOT the engine under test (so
        crafted foreign proposals are the only proposals in play)."""
        for height in range(1, 6):
            if self.leader(height) != self.engine.name:
                return height
        raise AssertionError("validator 0 always leads")

    def signed_proposal(self, height, round_=0, content=None, proposer=None,
                        signer=None, corrupt_sig=False):
        content = content if content is not None else self.adapter.content
        proposer = proposer or self.leader(height, round_)
        signer = signer or proposer
        p = Proposal(height=height, round=round_, content=content,
                     block_hash=sm3_hash(content), lock=None,
                     proposer=proposer)
        sig = self.by_addr[signer].sign(sm3_hash(p.encode()))
        if corrupt_sig:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        return SignedProposal(p, sig)

    def signed_vote(self, voter_crypto, height, round_, vote_type,
                    block_hash, corrupt_sig=False):
        v = Vote(height, round_, vote_type, block_hash)
        sig = voter_crypto.sign(sm3_hash(v.encode()))
        if corrupt_sig:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        return SignedVote(voter_crypto.pub_key, sig, v)

    def qc(self, height, round_, vote_type, block_hash, voters=None,
           forge_sig=False, bitmap_override=None):
        """A quorum certificate signed by `voters` (default: validators
        1..3 — a real quorum without the engine's own key)."""
        voters = voters if voters is not None else self.cryptos[1:]
        v = Vote(height, round_, vote_type, block_hash)
        vote_hash = sm3_hash(v.encode())
        pairs = sorted((c.pub_key, c.sign(vote_hash)) for c in voters)
        agg = self.cryptos[0].aggregate_signatures(
            [s for _, s in pairs], [a for a, _ in pairs])
        if forge_sig:
            agg = bytes([agg[0] ^ 1]) + agg[1:]
        bitmap = (bitmap_override if bitmap_override is not None
                  else build_bitmap(self.nodes, [a for a, _ in pairs]))
        return AggregatedVote(
            signature=AggregatedSignature(agg, bitmap),
            vote_type=vote_type, height=height, round=round_,
            block_hash=block_hash, leader=self.leader(height, round_))


def run(coro):
    asyncio.run(coro)


class TestQCForgery(unittest.TestCase):
    def test_valid_precommit_qc_commits(self):
        """Sanity: the attack-free QC drives a commit attempt — so the
        rejections below demonstrate the guards, not a broken harness."""
        async def main():
            h = EngineHarness()
            await h.start(height=1)
            h.engine.handler.send_msg(
                h.signed_proposal(1))  # engine needs the content to commit
            await h.settle()
            h.engine.handler.send_msg(
                h.qc(1, 0, VoteType.PRECOMMIT, h.adapter.block_hash))
            await h.settle()
            assert len(h.adapter.commits) == 1
            await h.stop()
        run(main())

    def test_forged_qc_signature_rejected(self):
        async def main():
            h = EngineHarness()
            await h.start(height=1)
            h.engine.handler.send_msg(h.signed_proposal(1))
            await h.settle()
            h.engine.handler.send_msg(
                h.qc(1, 0, VoteType.PRECOMMIT, h.adapter.block_hash,
                     forge_sig=True))
            await h.settle()
            assert h.adapter.commits == []
            await h.stop()
        run(main())

    def test_subquorum_bitmap_rejected(self):
        """A QC naming only 2 of 4 voters (< 2f+1) must be rejected even
        with valid signatures."""
        async def main():
            h = EngineHarness()
            await h.start(height=1)
            h.engine.handler.send_msg(h.signed_proposal(1))
            await h.settle()
            h.engine.handler.send_msg(
                h.qc(1, 0, VoteType.PRECOMMIT, h.adapter.block_hash,
                     voters=h.cryptos[1:3]))
            await h.settle()
            assert h.adapter.commits == []
            await h.stop()
        run(main())

    def test_tampered_padding_bit_rejected(self):
        """Setting a padding bit beyond the authority count must invalidate
        the bitmap (core/bitmap.py hardening): otherwise one aggregated
        signature would verify under multiple byte-distinct bitmaps."""
        async def main():
            h = EngineHarness()
            await h.start(height=1)
            h.engine.handler.send_msg(h.signed_proposal(1))
            await h.settle()
            good = h.qc(1, 0, VoteType.PRECOMMIT, h.adapter.block_hash)
            bitmap = bytearray(good.signature.address_bitmap)
            bitmap[-1] |= 1 << (7 - 4)  # bit index 4: first padding slot
            with self.assertRaises(ValueError):
                extract_voters(h.nodes, bytes(bitmap))
            h.engine.handler.send_msg(h.qc(
                1, 0, VoteType.PRECOMMIT, h.adapter.block_hash,
                bitmap_override=bytes(bitmap)))
            await h.settle()
            assert h.adapter.commits == []
            await h.stop()
        run(main())

    def test_wrong_length_bitmap_rejected(self):
        async def main():
            h = EngineHarness()
            await h.start(height=1)
            h.engine.handler.send_msg(h.signed_proposal(1))
            await h.settle()
            h.engine.handler.send_msg(h.qc(
                1, 0, VoteType.PRECOMMIT, h.adapter.block_hash,
                bitmap_override=b"\xe0\x00"))
            await h.settle()
            assert h.adapter.commits == []
            await h.stop()
        run(main())


class TestProposalAttacks(unittest.TestCase):
    def test_equivocating_leader_second_proposal_ignored(self):
        """Two distinct proposals for one (height, round) from the leader:
        only the first is adopted; the equivocation cannot split the
        engine's prevote."""
        async def main():
            h = EngineHarness()
            await h.start(height=1)
            a = h.signed_proposal(1, content=b"block A")
            b = h.signed_proposal(1, content=b"block B")
            h.engine.handler.send_msg(a)
            h.engine.handler.send_msg(b)
            await h.settle()
            # exactly one prevote cast, for block A
            votes = [SignedVote.decode(p) for r, t, p in h.adapter.transmits
                     if t == "SignedVote"]
            prevotes = [sv for sv in votes
                        if sv.vote.vote_type == VoteType.PREVOTE]
            assert len(prevotes) == 1
            assert prevotes[0].vote.block_hash == sm3_hash(b"block A")
            await h.stop()
        run(main())

    def test_non_leader_proposal_ignored(self):
        async def main():
            h = EngineHarness()
            await h.start(height=1)
            leader = h.leader(1)
            impostor = next(c.pub_key for c in h.cryptos
                            if c.pub_key != leader)
            h.engine.handler.send_msg(
                h.signed_proposal(1, proposer=impostor, signer=impostor))
            await h.settle()
            votes = [SignedVote.decode(p) for r, t, p in h.adapter.transmits
                     if t == "SignedVote"]
            assert all(sv.vote.block_hash != h.adapter.block_hash
                       for sv in votes)
            await h.stop()
        run(main())

    def test_bad_proposal_signature_ignored(self):
        async def main():
            h = EngineHarness()
            await h.start(height=1)
            h.engine.handler.send_msg(h.signed_proposal(1, corrupt_sig=True))
            await h.settle()
            votes = [SignedVote.decode(p) for r, t, p in h.adapter.transmits
                     if t == "SignedVote"]
            assert all(sv.vote.block_hash != h.adapter.block_hash
                       for sv in votes)
            await h.stop()
        run(main())


class TestVoteAttacks(unittest.TestCase):
    """Attacks on the leader's vote-collection path.  Height 4 makes the
    harness engine (sorted slot 0) the round-0 leader; as leader it
    proposes and self-delivers its OWN prevote, so the quorum of 3 needs
    two more distinct voters."""

    LEADER_HEIGHT = 4

    def test_duplicate_vote_replay_not_counted(self):
        """One distinct foreign voter plus replays of the same vote is 2 of
        the 3 needed — no QC; a second distinct voter completes it."""
        async def main():
            h = EngineHarness()
            height = self.LEADER_HEIGHT
            await h.start(height=height)
            await h.settle()
            bh = h.adapter.block_hash
            v1 = h.signed_vote(h.cryptos[1], height, 0, VoteType.PREVOTE, bh)
            for sv in (v1, v1, v1):
                h.engine.handler.send_msg(sv)
            await h.settle()
            qcs = [t for t, p in h.adapter.broadcasts
                   if t == "AggregatedVote"]
            assert qcs == [], "replayed votes must not reach quorum"
            # a second distinct voter completes the quorum
            h.engine.handler.send_msg(
                h.signed_vote(h.cryptos[2], height, 0, VoteType.PREVOTE, bh))
            await h.settle()
            qcs = [t for t, p in h.adapter.broadcasts
                   if t == "AggregatedVote"]
            assert len(qcs) >= 1
            await h.stop()
        run(main())

    def test_forged_vote_signature_not_counted(self):
        async def main():
            h = EngineHarness()
            height = self.LEADER_HEIGHT
            await h.start(height=height)
            await h.settle()
            bh = h.adapter.block_hash
            h.engine.handler.send_msg(
                h.signed_vote(h.cryptos[1], height, 0, VoteType.PREVOTE, bh))
            h.engine.handler.send_msg(
                h.signed_vote(h.cryptos[2], height, 0, VoteType.PREVOTE, bh,
                              corrupt_sig=True))
            h.engine.handler.send_msg(
                h.signed_vote(h.cryptos[3], height, 0, VoteType.PREVOTE, bh,
                              corrupt_sig=True))
            await h.settle()
            qcs = [t for t, p in h.adapter.broadcasts
                   if t == "AggregatedVote"]
            assert qcs == [], "forged votes must not reach quorum"
            await h.stop()
        run(main())

    def test_non_validator_vote_ignored(self):
        async def main():
            h = EngineHarness()
            height = self.LEADER_HEIGHT
            await h.start(height=height)
            await h.settle()
            bh = h.adapter.block_hash
            # The engine (leader) votes for itself, so only ONE more valid
            # vote may arrive: self + cryptos[1] + outsider = quorum iff the
            # outsider's (validly self-signed) vote is wrongly counted.
            outsider = sim_crypto(b"\x77" * 32)
            h.engine.handler.send_msg(
                h.signed_vote(h.cryptos[1], height, 0, VoteType.PREVOTE, bh))
            h.engine.handler.send_msg(
                h.signed_vote(outsider, height, 0, VoteType.PREVOTE, bh))
            await h.settle()
            qcs = [t for t, p in h.adapter.broadcasts
                   if t == "AggregatedVote"]
            assert qcs == [], "an outsider vote must not complete a quorum"
            await h.stop()
        run(main())


class TestRandomizedSchedules(unittest.TestCase):
    def test_fork_invariant_under_adversarial_network(self):
        """Randomized drop/delay schedules: the run may be slow but never
        forks (SimController raises SafetyViolation on any conflicting
        commit) and must stay live enough to reach height 2."""
        async def one(seed):
            net = SimNetwork(4, block_interval_ms=50, seed=seed,
                             drop_rate=0.15, delay_range=(0.0, 0.08))
            net.start()
            try:
                await net.run_until_height(2, timeout=60.0)
            finally:
                await net.stop()

        async def main():
            for seed in (11, 29, 43):
                await one(seed)

        run(main())


if __name__ == "__main__":
    unittest.main()


class TestRoundFloodMemory(unittest.TestCase):
    def test_round_flood_memory_bounded(self):
        """A single valid validator spraying votes/chokes across a huge
        round range must not grow the per-round maps beyond the live
        window (Engine.ROUND_WINDOW): memory stays O(window), not
        O(rounds sprayed)."""
        from consensus_overlord_tpu.core.types import Choke, SignedChoke

        async def main():
            h = EngineHarness()
            await h.start(1)
            eng = h.engine
            height = eng.height
            attacker = h.cryptos[1]
            window = eng.ROUND_WINDOW

            # Chokes: rounds 0..199 (only ≤ window accepted) plus a spray
            # of far-future rounds (all rejected).
            for r in list(range(200)) + [10**6 + i for i in range(50)]:
                c = Choke(height, r)
                sig = attacker.sign(sm3_hash(c.encode()))
                eng.handler.send_msg(SignedChoke(sig, attacker.pub_key, c))
            # Votes: every round the engine leads in 0..199 plus far spray.
            for r in list(range(200)) + [10**6 + i for i in range(50)]:
                if eng.leader(height, r) != eng.name:
                    continue
                eng.handler.send_msg(h.signed_vote(
                    attacker, height, r, VoteType.PREVOTE,
                    h.adapter.block_hash))
            await h.settle(0.5)

            cur = eng.round
            assert len(eng._chokes) <= 2 * window + 2, len(eng._chokes)
            assert all(r <= cur + window for r in eng._chokes)
            assert len(eng._prevotes) <= 2 * window + 2, len(eng._prevotes)
            assert all(abs(r - cur) <= window for r in eng._prevotes)
            # Sanity: in-window messages were NOT dropped — the guard
            # bounds memory without breaking collection.
            assert eng._chokes, "in-window chokes should be collected"
            assert eng._prevotes, "in-window votes should be collected"
            await h.stop()

        run(main())
