"""Worker process for the two-process DCN smoke test (test_multihost.py).

Invoked as: python dcn_worker.py <process_id> <num_processes> <coordinator>

Each process brings 2 virtual CPU devices into a jax.distributed runtime
(the DCN analog this environment can execute), builds the host-major
global mesh, and runs the PRODUCTION sharded verify-round kernel over a
batch that spans both processes' devices — asserting the replicated MSM
aggregates against the host oracle.  Prints "DCN-OK" on success.
"""

import os
import sys

PID = int(sys.argv[1])
NPROC = int(sys.argv[2])
COORD = sys.argv[3]

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from consensus_overlord_tpu.compile_cache import enable  # noqa: E402

enable()

# Join the distributed runtime BEFORE anything touches the XLA backend —
# the ops modules build jnp constants at import time, which would
# initialize a single-process backend and make jax.distributed refuse.
from consensus_overlord_tpu.parallel.multihost import (  # noqa: E402
    global_mesh, init_multihost)

_JOINED = init_multihost(COORD, NPROC, PID)

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from consensus_overlord_tpu.core.sm3 import sm3_hash  # noqa: E402
from consensus_overlord_tpu.crypto import bls12381 as oracle  # noqa: E402
from consensus_overlord_tpu.ops import bls12381_groups as dev  # noqa: E402
from consensus_overlord_tpu.parallel import (  # noqa: E402
    sharded_verify_round)


def main() -> None:
    assert _JOINED, "coordinator join failed"
    assert jax.process_count() == NPROC, jax.process_count()
    mesh = global_mesh()
    assert mesh.devices.size == 2 * NPROC, mesh.devices.size

    # Deterministic batch (both processes build identical host data).
    batch = 8
    h = sm3_hash(b"dcn-smoke-block")
    sks = [9000 + 17 * i for i in range(batch)]
    sigs = [oracle.sign(sk, h) for sk in sks]
    pks_aff = [oracle.g2_decompress(oracle.sk_to_pk(sk)) for sk in sks]
    parsed = dev.parse_g1_compressed(sigs)
    scalars = [(0x9E3779B9 * (i + 1)) | (1 << 63) for i in range(batch)]
    wpacked = np.frombuffer(
        b"".join(s.to_bytes(8, "big") for s in scalars),
        np.uint8).reshape(batch, 8).copy()
    rows = np.arange(batch, dtype=np.int64)
    pk = dev.g2_from_oracle(pks_aff)
    pkx, pky, pkz = (np.asarray(pk.x), np.asarray(pk.y), np.asarray(pk.z))

    shard = NamedSharding(mesh, P("lanes"))
    repl = NamedSharding(mesh, P())

    def dist(arr, sharding):
        arr = np.asarray(arr)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    fn = sharded_verify_round(mesh)
    out = fn(dist(parsed.x, shard), dist(parsed.sign, shard),
             dist(parsed.infinity, shard), dist(parsed.wellformed, shard),
             dist(wpacked, shard), dist(rows, shard),
             dist(pkx, repl), dist(pky, repl), dist(pkz, repl))
    ax, ay, ainf, valid, gx, gy, ginf = out
    # Replicated outputs are process-local readable; `valid` is sharded —
    # check this process's addressable shards only.
    for s in valid.addressable_shards:
        assert bool(np.asarray(s.data).all()), "invalid lane in local shard"
    got_g1 = (dev.FQ.ints_from_strict(np.asarray(ax))[0],
              dev.FQ.ints_from_strict(np.asarray(ay))[0])
    want = None
    for s, r in zip(sigs, scalars):
        want = oracle.g1_add(want, oracle.g1_mul(oracle.g1_decompress(s), r))
    assert got_g1 == want, "G1 RLC aggregate disagrees with oracle over DCN"
    want2 = None
    for p, r in zip(pks_aff, scalars):
        want2 = oracle.g2_add(want2, oracle.g2_mul(p, r))
    got_g2 = (tuple(dev.FQ.ints_from_strict(np.asarray(gx))),
              tuple(dev.FQ.ints_from_strict(np.asarray(gy))))
    assert got_g2 == want2, "G2 RLC aggregate disagrees with oracle over DCN"
    print("DCN-OK", flush=True)


if __name__ == "__main__":
    main()
