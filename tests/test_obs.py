"""Observability layer: frontier/engine/WAL metrics, the flight
recorder, the /statusz endpoint, real gRPC status codes in the RPC
counter, the compile-cache satellites (model-name fingerprint,
prune-only-default-root), and the device-profiling layer (obs/prof.py:
staged round profiles, occupancy gauge, ProfileSession no-op/capture
behavior, frontier flush reasons, /debug/profile trigger)."""

import asyncio
import json
import os
import tempfile
import unittest
import urllib.error
import urllib.request
from unittest import mock

import grpc

from consensus_overlord_tpu import compile_cache
from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.core.types import Node, VoteType
from consensus_overlord_tpu.crypto.frontier import BatchingVerifier
from consensus_overlord_tpu.crypto.provider import CpuBlsCrypto
from consensus_overlord_tpu.engine.smr import Engine
from consensus_overlord_tpu.engine.wal import FileWal, MemoryWal
from consensus_overlord_tpu.obs import FlightRecorder, Metrics, snapshot
from consensus_overlord_tpu.service.pb import pb2
from consensus_overlord_tpu.service.rpc import (
    HEALTH_SERVICE,
    RetryClient,
    generic_handler,
)
from consensus_overlord_tpu.sim.harness import SimNetwork

from test_byzantine import EngineHarness, StubAdapter  # noqa: E402


def run(coro):
    return asyncio.run(coro)


class BlsEngineHarness(EngineHarness):
    """test_byzantine's EngineHarness over the dependency-free CPU BLS
    provider (Ed25519Crypto needs the absent `cryptography` package)."""

    def __init__(self):
        cryptos = [CpuBlsCrypto(0x5EED + 31 * i) for i in range(4)]
        cryptos.sort(key=lambda c: c.pub_key)
        self.cryptos = cryptos
        self.by_addr = {c.pub_key: c for c in cryptos}
        self.nodes = [Node(c.pub_key) for c in cryptos]
        self.adapter = StubAdapter()
        self.engine = Engine(cryptos[0].pub_key, self.adapter,
                             cryptos[0], MemoryWal())


# ---------------------------------------------------------------------------
# frontier metrics
# ---------------------------------------------------------------------------

class FrontierMetrics(unittest.TestCase):
    def test_flush_observes_batch_shape_and_failures(self):
        """Every flush lands in frontier_batch_size; each request's wait
        lands in frontier_queue_wait_ms; a bad signature counts into
        frontier_verify_failures_total under its message type."""
        async def main():
            crypto = CpuBlsCrypto(0xC0FFEE)
            m = Metrics()
            fr = BatchingVerifier(crypto, max_batch=64, linger_s=0.005,
                                  metrics=m)
            h = sm3_hash(b"payload")
            good = crypto.sign(h)
            bad = bytes([good[0] ^ 1]) + good[1:]
            results = await asyncio.gather(
                fr.verify(good, h, crypto.pub_key, msg_type="SignedVote"),
                fr.verify(good, h, crypto.pub_key, msg_type="SignedVote"),
                fr.verify(bad, h, crypto.pub_key, msg_type="SignedChoke"))
            fr.close()
            self.assertEqual(results, [True, True, False])
            s = snapshot(m.registry)
            self.assertGreaterEqual(s["frontier_batch_size_count"], 1)
            self.assertEqual(s["frontier_batch_size_sum"], 3)
            self.assertEqual(s["frontier_queue_wait_ms_count"], 3)
            self.assertEqual(
                s["frontier_verify_failures_total{msg_type=SignedChoke}"],
                1)
            self.assertNotIn(
                "frontier_verify_failures_total{msg_type=SignedVote}", s)
        run(main())

    def test_provider_error_counts_once_not_per_lane(self):
        """An infra error (provider raises) must land ONCE under
        msg_type="batch_error", never inflate the per-type counters."""
        class Exploding:
            def verify_batch(self, sigs, hashes, voters):
                raise RuntimeError("device fell over")

        async def main():
            m = Metrics()
            fr = BatchingVerifier(Exploding(), max_batch=4,
                                  linger_s=0.001, metrics=m)
            results = await asyncio.gather(
                *(fr.verify(b"s", b"h", b"v", msg_type="SignedVote")
                  for _ in range(3)))
            fr.close()
            self.assertEqual(results, [False, False, False])
            s = snapshot(m.registry)
            self.assertEqual(
                s["frontier_verify_failures_total{msg_type=batch_error}"],
                1)
            self.assertNotIn(
                "frontier_verify_failures_total{msg_type=SignedVote}", s)
        run(main())

    def test_occupancy_observed_where_provider_pads(self):
        """Occupancy/padded-lanes come from TpuBlsCrypto._host_prep —
        the single point every device batch (fused or split sub-batch)
        passes through; below-threshold host batches never observe."""
        from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto

        m = Metrics()
        p = TpuBlsCrypto(0xFEED, device_threshold=2)
        p.bind_metrics(m)
        h = sm3_hash(b"block")
        sigs = [p.sign(h) for _ in range(3)]
        voters = [p.pub_key] * 3
        p._host_prep(sigs, voters, 3)  # device prep: pads 3 → ladder 8
        s = snapshot(m.registry)
        self.assertEqual(s["frontier_batch_occupancy_count"], 1)
        self.assertAlmostEqual(s["frontier_batch_occupancy_sum"], 3 / 8)
        self.assertEqual(s["frontier_padded_lanes_total"], 5)
        # Below the device threshold the host path runs — no padding,
        # no occupancy observation.
        resolve = p.verify_batch_async(sigs[:1], [h], voters[:1])
        self.assertEqual(resolve(), [True])
        s = snapshot(m.registry)
        self.assertEqual(s["frontier_batch_occupancy_count"], 1)


# ---------------------------------------------------------------------------
# real gRPC status codes
# ---------------------------------------------------------------------------

class InterceptorCodes(unittest.TestCase):
    def test_records_abort_code_not_binary_error(self):
        """An aborted RPC must count under its REAL status code
        (INVALID_ARGUMENT here), a clean return under OK."""
        class _Health:
            async def check(self, request, context):
                if request.service == "abort":
                    await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                        "bad request")
                return pb2.HealthCheckResponse(
                    status=pb2.HealthCheckResponse.SERVING)

        async def main():
            m = Metrics()
            server = grpc.aio.server(interceptors=[m.interceptor()])
            server.add_generic_rpc_handlers(
                (generic_handler("Health", HEALTH_SERVICE, _Health()),))
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            try:
                client = RetryClient(f"127.0.0.1:{port}", "Health",
                                     HEALTH_SERVICE, retries=1)
                await client.call("Check",
                                  pb2.HealthCheckRequest(service=""))
                with self.assertRaises(grpc.aio.AioRpcError) as ctx:
                    await client.call(
                        "Check", pb2.HealthCheckRequest(service="abort"))
                self.assertEqual(ctx.exception.code(),
                                 grpc.StatusCode.INVALID_ARGUMENT)
                await client.close()
            finally:
                await server.stop(0.2)
            s = snapshot(m.registry)
            method = [k for k in s
                      if k.startswith("grpc_server_handled_total")
                      and "code=OK" in k]
            self.assertEqual(len(method), 1)
            self.assertEqual(s[method[0]], 1)
            aborted = [k for k in s
                       if k.startswith("grpc_server_handled_total")
                       and "code=INVALID_ARGUMENT" in k]
            self.assertEqual(len(aborted), 1)
            self.assertEqual(s[aborted[0]], 1)
        run(main())


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorderRing(unittest.TestCase):
    def test_bounded_ring_and_tail_order(self):
        rec = FlightRecorder(capacity=4)
        for i in range(10):
            rec.record("tick", i=i)
        self.assertEqual(len(rec), 4)
        tail = rec.tail()
        self.assertEqual([e["i"] for e in tail], [6, 7, 8, 9])
        self.assertEqual([e["i"] for e in rec.tail(2)], [8, 9])
        self.assertEqual(rec.tail(0), [])  # 0 = none, not everything
        dump = rec.dump()
        self.assertIn("tick", dump)
        self.assertIn("i=9", dump)

    def test_byzantine_rejection_recorded_and_dumpable(self):
        """A forged QC leaves a qc_rejected event in the ring — the
        post-mortem trail for a Byzantine test failure."""
        async def main():
            h = BlsEngineHarness()
            h.engine.recorder = FlightRecorder(64)
            await h.start(height=1)
            h.engine.handler.send_msg(h.signed_proposal(1))
            await h.settle(0.5)  # pure-python BLS verify needs headroom
            h.engine.handler.send_msg(
                h.qc(1, 0, VoteType.PRECOMMIT, h.adapter.block_hash,
                     forge_sig=True))
            await h.settle(1.0)
            self.assertEqual(h.adapter.commits, [])
            kinds = [e["kind"] for e in h.engine.recorder.tail()]
            self.assertIn("enter_round", kinds)
            self.assertIn("qc_rejected", kinds)
            dump = h.engine.recorder.dump()
            self.assertIn("qc_rejected", dump)
            self.assertIn("vote_type='PRECOMMIT'", dump)
            await h.stop()
        run(main())


# ---------------------------------------------------------------------------
# statusz endpoint
# ---------------------------------------------------------------------------

class Statusz(unittest.TestCase):
    def test_statusz_json_shape_and_metrics_coexist(self):
        m = Metrics()
        rec = FlightRecorder(16)
        rec.record("enter_round", height=3, round=1)
        m.add_status_source("consensus",
                            lambda: {"height": 3, "round": 1,
                                     "leader": "ab12"})
        m.add_status_source("flightrec", lambda: rec.tail(8))
        m.add_status_source("broken", lambda: 1 / 0)
        m.frontier_batch_size.observe(7)
        port = m.start_exporter(0, addr="127.0.0.1")
        try:
            def get(path):
                return urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5)

            doc = json.load(get("/statusz"))
            self.assertEqual(doc["consensus"]["height"], 3)
            self.assertEqual(doc["consensus"]["round"], 1)
            self.assertEqual(doc["flightrec"][-1]["kind"], "enter_round")
            self.assertIn("error", doc["broken"])  # degraded, not down
            doc2 = json.load(get("/debug/vars"))
            self.assertEqual(doc2["consensus"]["leader"], "ab12")
            body = get("/metrics").read()
            self.assertIn(b"frontier_batch_size_bucket", body)
            with self.assertRaises(urllib.error.HTTPError) as ctx:
                get("/nonexistent")
            self.assertEqual(ctx.exception.code, 404)
        finally:
            m.stop_exporter()

    def test_statusz_loopback_gate(self):
        """statusz is loopback-only by default — remote peers get the
        403, loopback (incl. v4-mapped v6) passes."""
        from consensus_overlord_tpu.obs.metrics import _loopback
        self.assertTrue(_loopback("127.0.0.1"))
        self.assertTrue(_loopback("127.0.0.53"))
        self.assertTrue(_loopback("::1"))
        self.assertTrue(_loopback("::ffff:127.0.0.1"))
        self.assertFalse(_loopback("10.0.0.7"))
        self.assertFalse(_loopback("::ffff:10.0.0.7"))
        self.assertFalse(_loopback("2001:db8::1"))


# ---------------------------------------------------------------------------
# WAL latency
# ---------------------------------------------------------------------------

class WalMetrics(unittest.TestCase):
    def test_file_wal_observes_append_and_fsync(self):
        async def main():
            m = Metrics()
            with tempfile.TemporaryDirectory() as tmp:
                wal = FileWal(tmp, metrics=m)
                await wal.save(b"state-1")
                await wal.save(b"state-2")
                self.assertEqual(await wal.load(), b"state-2")
            s = snapshot(m.registry)
            self.assertEqual(s["wal_append_ms_count"], 2)
            self.assertEqual(s["wal_fsync_ms_count"], 2)
            self.assertGreater(s["wal_append_ms_sum"], 0)
        run(main())

    def test_memory_wal_observes_append(self):
        async def main():
            m = Metrics()
            wal = MemoryWal(metrics=m)
            await wal.save(b"x")
            self.assertEqual(snapshot(m.registry)["wal_append_ms_count"], 1)
        run(main())


# ---------------------------------------------------------------------------
# engine metrics through the sim fleet (the acceptance-criteria path)
# ---------------------------------------------------------------------------

class SimFleetMetrics(unittest.TestCase):
    def test_fleet_exports_round_wal_and_frontier_metrics(self):
        """A 4-validator sim run exports non-zero frontier_batch_size,
        round-duration, and WAL-latency metrics from one shared registry,
        and every node's flight recorder saw its state transitions."""
        async def main():
            m = Metrics()
            # interval 1 s: round timers scale off it, and pure-Python
            # BLS on a loaded 1-core box needs the headroom to beat the
            # timeouts (same rationale as test_service's 2 s interval).
            net = SimNetwork(n_validators=4, block_interval_ms=1000,
                             use_frontier=True, frontier_linger_s=0.002,
                             crypto_factory=lambda i: CpuBlsCrypto(
                                 0x1000 + 7919 * i),
                             metrics=m, flight_recorder_capacity=64)
            net.start(init_height=1)
            await net.run_until_height(1, timeout=90)
            # Let the fleet process the height-1 commit/status fan-out so
            # the round-transition observations land before the scrape.
            await asyncio.sleep(0.8)
            await net.stop()
            s = snapshot(m.registry)
            self.assertGreater(s["frontier_batch_size_count"], 0)
            self.assertGreater(s["consensus_round_duration_ms_count"], 0)
            self.assertGreater(s["wal_append_ms_count"], 0)
            self.assertGreater(
                s["consensus_committed_heights_total"], 0)
            for node in net.nodes:
                kinds = [e["kind"] for e in node.recorder.tail()]
                self.assertIn("enter_round", kinds)
            dump = net.dump_flight_recorders(8)
            self.assertIn("enter_round", dump)
        run(main())


# ---------------------------------------------------------------------------
# engine GC satellite: choke-round histogram pruning
# ---------------------------------------------------------------------------

class ChokeHistGC(unittest.TestCase):
    def test_choke_round_hist_pruned_with_live_window(self):
        async def main():
            h = BlsEngineHarness()
            await h.start(height=1)
            eng = h.engine
            eng._choke_round_hist.update({0: 1, 3: 2, 30: 3})
            floor_round = eng.ROUND_WINDOW + 10  # floor = 10
            await eng._enter_round(floor_round)
            self.assertNotIn(0, eng._choke_round_hist)
            self.assertNotIn(3, eng._choke_round_hist)
            self.assertIn(30, eng._choke_round_hist)
            await h.stop()
        run(main())


# ---------------------------------------------------------------------------
# device profiling layer (obs/prof.py)
# ---------------------------------------------------------------------------

class DeviceProfiling(unittest.TestCase):
    def test_sim_provider_populates_stage_metrics(self):
        """A verify_batch through the simulated device path records a
        staged profile: crypto_device_stage_seconds counts, a ring
        record, and occupancy 1.0 (sim batches ship unpadded)."""
        from consensus_overlord_tpu.crypto.provider import (
            SimDeviceCrypto,
            SimHashCrypto,
        )
        from consensus_overlord_tpu.obs import DeviceProfiler

        m = Metrics()
        prof = DeviceProfiler(m, capacity=8)
        c = SimDeviceCrypto(SimHashCrypto(b"\x01" * 32))
        c.bind_metrics(m)
        c.bind_profiler(prof)
        h = c.hash(b"block")
        sigs = [c.sign(h)] * 3
        self.assertEqual(c.verify_batch(sigs, [h] * 3, [c.pub_key] * 3),
                         [True, True, True])
        c.aggregate_signatures(sigs, [c.pub_key] * 3)
        s = snapshot(m.registry)
        self.assertEqual(
            s["crypto_device_stage_seconds_count"
              "{op=verify_batch,stage=dispatch}"], 1)
        self.assertEqual(
            s["crypto_device_stage_seconds_count"
              "{op=aggregate,stage=dispatch}"], 1)
        self.assertEqual(s["crypto_device_batch_occupancy"], 1.0)
        totals = prof.stage_totals()
        self.assertGreater(totals["verify_batch/dispatch"]["count"], 0)
        tail = prof.tail()
        self.assertEqual([r["op"] for r in tail],
                         ["verify_batch", "aggregate"])
        self.assertEqual(tail[0]["batch"], 3)
        self.assertTrue(tail[0]["ok"])

    def test_occupancy_gauge_reflects_padding(self):
        """The occupancy gauge tracks real/padded lanes where the pad is
        computed (TpuBlsCrypto._host_prep): 3 lanes on the 8-rung →
        0.375, in (0, 1]."""
        from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
        from consensus_overlord_tpu.obs import DeviceProfiler

        m = Metrics()
        prof = DeviceProfiler(m)
        p = TpuBlsCrypto(0xFEED, device_threshold=2)
        p.bind_metrics(m)
        p.bind_profiler(prof)
        h = sm3_hash(b"block")
        sigs = [p.sign(h) for _ in range(3)]
        call = prof.begin("verify_batch", 3)
        p._host_prep(sigs, [p.pub_key] * 3, 3, call=call)
        call.finish()
        s = snapshot(m.registry)
        self.assertAlmostEqual(s["crypto_device_batch_occupancy"], 3 / 8)
        self.assertGreater(s["crypto_device_batch_occupancy"], 0)
        self.assertLessEqual(s["crypto_device_batch_occupancy"], 1)
        self.assertEqual(prof.tail()[-1]["padded"], 8)
        # bind_profiler announced the dispatch device set.
        self.assertEqual(s["mesh_devices"], 1)

    def test_statusz_profile_section_and_debug_trigger(self):
        """/statusz carries the "profile" section; /debug/profile is
        loopback-gated, parses ?rounds=, and reports why a capture
        can't start when no profile_dir is configured."""
        from consensus_overlord_tpu.obs import DeviceProfiler, ProfileSession

        m = Metrics()
        prof = DeviceProfiler(m, capacity=4)
        session = ProfileSession(None)
        call = prof.begin("verify_batch", 2)
        call.observe("dispatch", 0.001)
        call.finish()
        m.add_status_source(
            "profile", lambda: {**prof.statusz(),
                                "session": session.status()})
        m.add_debug_handler(
            "/debug/profile",
            lambda q: session.request(int(q.get("rounds", "1"))))
        port = m.start_exporter(0, addr="127.0.0.1")
        try:
            doc = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz", timeout=5))
            self.assertIn("profile", doc)
            self.assertEqual(doc["profile"]["recent"][0]["op"],
                             "verify_batch")
            self.assertIn("crypto_device_stage_seconds", doc["profile"])
            self.assertFalse(doc["profile"]["session"]["available"])
            reply = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile?rounds=3",
                timeout=5))
            self.assertFalse(reply["ok"])
            self.assertIn("profile_dir", reply["reason"])
        finally:
            m.stop_exporter()

    def test_profile_session_noops_without_dir_or_jax(self):
        """No profile_dir, or no jax.profiler: every entry point is a
        clean no-op — start() False, on_round() silent, stop() None."""
        from consensus_overlord_tpu.obs import prof as prof_mod

        session = prof_mod.ProfileSession(None, every_n_rounds=1)
        self.assertFalse(session.available)
        self.assertFalse(session.start())
        for r in range(3):
            session.on_round(1, r)  # must not raise or capture
        self.assertIsNone(session.stop())
        self.assertFalse(session.request(2)["ok"])
        # jax.profiler unavailable: configured dir changes nothing.
        with mock.patch.object(prof_mod, "_profiler_mod", None), \
                mock.patch.object(prof_mod, "_profiler_checked", True):
            session = prof_mod.ProfileSession("/tmp/nowhere", 1)
            self.assertFalse(session.available)
            self.assertFalse(session.start())
            session.on_round(1, 0)
            self.assertIsNone(session.stop())
            self.assertFalse(session.request(1)["ok"])
            # annotate degrades to a nullcontext, not an error.
            with prof_mod.annotate("noop"):
                pass

    def test_profile_session_round_cadence_and_capture(self):
        """With a profile_dir: on_round opens a capture on the
        every_n_rounds cadence and closes it a round later, leaving a
        non-empty trace directory."""
        from consensus_overlord_tpu.obs import ProfileSession

        with tempfile.TemporaryDirectory() as tmp:
            session = ProfileSession(tmp, every_n_rounds=2)
            if not session.available:  # no jax.profiler in this env
                self.skipTest("jax.profiler unavailable")
            import jax.numpy as jnp

            session.on_round(1, 0)  # round_ix 1: no capture
            self.assertFalse(session.active)
            session.on_round(1, 1)  # round_ix 2: capture opens
            self.assertTrue(session.active)
            jnp.arange(4).block_until_ready()  # something to trace
            session.on_round(1, 2)  # budget spent: capture closes
            self.assertFalse(session.active)
            files = [os.path.join(r, f)
                     for r, _, fs in os.walk(tmp) for f in fs]
            self.assertTrue(files, "capture left no trace files")
            self.assertIsNotNone(session.status()["last_capture_dir"])


# ---------------------------------------------------------------------------
# frontier flush reasons
# ---------------------------------------------------------------------------

class FrontierFlushReason(unittest.TestCase):
    def test_linger_and_max_batch_reasons_counted(self):
        """A size-triggered flush counts under max_batch; a timer
        flush under linger — the queue-wait histogram's decoder ring."""
        async def main():
            crypto = CpuBlsCrypto(0xC0FFEE)
            m = Metrics()
            fr = BatchingVerifier(crypto, max_batch=2, linger_s=0.005,
                                  metrics=m)
            h = sm3_hash(b"payload")
            good = crypto.sign(h)
            # Two concurrent requests hit max_batch=2 and flush on size.
            await asyncio.gather(
                fr.verify(good, h, crypto.pub_key),
                fr.verify(good, h, crypto.pub_key))
            # A lone request can only leave via the linger timer.
            await fr.verify(good, h, crypto.pub_key)
            fr.close()
            s = snapshot(m.registry)
            self.assertEqual(
                s["frontier_flush_reason_total{reason=max_batch}"], 1)
            self.assertEqual(
                s["frontier_flush_reason_total{reason=linger}"], 1)
            self.assertNotIn(
                "frontier_flush_reason_total{reason=shutdown}", s)
        run(main())


# ---------------------------------------------------------------------------
# compile-cache satellites
# ---------------------------------------------------------------------------

class CompileCacheSatellites(unittest.TestCase):
    def test_fingerprint_distinguishes_cpu_models(self):
        """Identical flags + different `model name` must land in
        different namespaces (XLA tunes LLVM features per model)."""
        flags = "flags\t\t: fpu vme de pse sse sse2\n"
        with tempfile.TemporaryDirectory() as tmp:
            a = os.path.join(tmp, "a")
            b = os.path.join(tmp, "b")
            c = os.path.join(tmp, "c")
            with open(a, "w") as f:
                f.write("model name\t: Intel(R) Xeon(R) CPU E5-2690\n"
                        + flags)
            with open(b, "w") as f:
                f.write("model name\t: AMD EPYC 7B12\n" + flags)
            with open(c, "w") as f:
                f.write("model name\t: Intel(R) Xeon(R) CPU E5-2690\n"
                        + flags)
            fa = compile_cache._host_fingerprint(a)
            fb = compile_cache._host_fingerprint(b)
            fc = compile_cache._host_fingerprint(c)
            self.assertNotEqual(fa, fb)
            self.assertEqual(fa, fc)

    def test_prune_legacy_never_touches_foreign_roots(self):
        """A user-supplied shared cache root must survive enable();
        only the repo-default root is pruned of flat legacy entries."""
        with tempfile.TemporaryDirectory() as tmp:
            legacy = os.path.join(tmp, "xla-cache")
            with open(legacy, "w") as f:
                f.write("someone else's live entry")
            compile_cache._prune_legacy(tmp)  # foreign root: no-op
            self.assertTrue(os.path.exists(legacy))
            with mock.patch.object(compile_cache, "_DEFAULT_DIR", tmp):
                compile_cache._prune_legacy(tmp)  # default root: pruned
            self.assertFalse(os.path.exists(legacy))

    def test_stats_counts_monitoring_events(self):
        before = compile_cache.stats()
        compile_cache._on_event("/jax/compilation_cache/cache_hits")
        compile_cache._on_event("/jax/compilation_cache/cache_misses")
        compile_cache._on_event("/jax/some/other/event")
        after = compile_cache.stats()
        self.assertEqual(after["hits"], before["hits"] + 1)
        self.assertEqual(after["misses"], before["misses"] + 1)


if __name__ == "__main__":
    unittest.main()
