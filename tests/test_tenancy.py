"""Multi-tenant crypto-as-a-service (crypto/tenancy.py): DWRR fairness,
priority lanes, bounded-queue admission/shed, and the single-tenant
refactor's behavior identity with the old BatchingVerifier."""

import asyncio
import time

import pytest

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto.frontier import BatchingVerifier
from consensus_overlord_tpu.crypto.provider import sim_crypto
from consensus_overlord_tpu.crypto.tenancy import SharedFrontier
from consensus_overlord_tpu.obs import Metrics, snapshot


def run(coro):
    return asyncio.run(coro)


class RecordingProvider:
    """Deterministic fake device: verify_batch records the voter order
    of every composed batch (the fairness/priority observable) and
    verdicts are table-driven — a voter starting with b"bad" fails.
    verify_signature is the exact host-oracle twin."""

    def __init__(self, batch_cost_s: float = 0.0):
        self.batches = []
        self.host_verifies = []
        self.batch_cost_s = batch_cost_s

    @staticmethod
    def _verdict(sig, h, voter) -> bool:
        return not bytes(voter).startswith(b"bad")

    def verify_batch(self, sigs, hashes, voters):
        self.batches.append([bytes(v) for v in voters])
        if self.batch_cost_s:
            time.sleep(self.batch_cost_s)
        return [self._verdict(s, h, v)
                for s, h, v in zip(sigs, hashes, voters)]

    def verify_signature(self, sig, h, voter):
        self.host_verifies.append(bytes(voter))
        return self._verdict(sig, h, voter)


async def enqueue(lane, voters, critical=False, msg_type="raw"):
    """Start one verify task per voter and yield until all are queued
    (or shed) — returns the tasks for later awaiting."""
    tasks = [asyncio.get_running_loop().create_task(
        lane.verify(b"s", b"h" * 16, v, msg_type=msg_type,
                    critical=critical)) for v in voters]
    for _ in range(4):
        await asyncio.sleep(0)
    return tasks


class TestDwrrFairness:
    def test_light_tenant_rides_every_batch(self):
        """100 heavy + 4 light pending, max_batch 10: the composed batch
        interleaves both and carries ALL light entries — a flooding
        tenant only fills the slack, it cannot push a light tenant out."""
        async def go():
            prov = RecordingProvider()
            core = SharedFrontier(prov, max_batch=10_000, linger_s=30.0)
            heavy = core.register("heavy", queue_bound=1000)
            light = core.register("light", queue_bound=1000)
            ht = await enqueue(heavy, [b"H%03d" % i for i in range(100)])
            lt = await enqueue(light, [b"L%03d" % i for i in range(4)])
            core._max_batch = 10  # compose under a tight cap, no auto-flush
            batch = core._compose_batch()
            voters = [e[2] for e in batch]
            assert len(batch) == 10
            assert sum(v.startswith(b"L") for v in voters) == 4
            assert sum(v.startswith(b"H") for v in voters) == 6
            for e in batch:  # resolve so the tasks can finish
                e[3].set_result(True)
            core.close()
            for t in ht + lt:
                await t
        run(go())

    def test_weights_split_the_batch(self):
        """weight 3 vs 1 at equal backlog: a 16-entry batch splits 12/4."""
        async def go():
            prov = RecordingProvider()
            core = SharedFrontier(prov, max_batch=10_000, linger_s=30.0)
            a = core.register("a", weight=3, queue_bound=1000)
            b = core.register("b", weight=1, queue_bound=1000)
            at = await enqueue(a, [b"A%03d" % i for i in range(50)])
            bt = await enqueue(b, [b"B%03d" % i for i in range(50)])
            core._max_batch = 16
            batch = core._compose_batch()
            voters = [e[2] for e in batch]
            assert sum(v.startswith(b"A") for v in voters) == 12
            assert sum(v.startswith(b"B") for v in voters) == 4
            for e in batch:
                e[3].set_result(True)
            core.close()
            for t in at + bt:
                await t
        run(go())

    def test_deficit_carries_over_a_cut_short_turn(self):
        """A turn truncated by the batch cap is repaid next flush: the
        shortfall persists in the lane's deficit."""
        async def go():
            prov = RecordingProvider()
            core = SharedFrontier(prov, max_batch=10_000, linger_s=30.0)
            a = core.register("a", weight=4, queue_bound=1000)
            at = await enqueue(a, [b"A%03d" % i for i in range(10)])
            core._max_batch = 2
            batch = core._compose_batch()
            assert len(batch) == 2
            # weight 4 earned, 2 spent: 2 carry over.
            assert a._deficit == pytest.approx(2.0)
            for e in batch:
                e[3].set_result(True)
            core.close()
            for t in at:
                await t
        run(go())

    def test_register_is_idempotent(self):
        prov = RecordingProvider()
        core = SharedFrontier(prov)
        lane = core.register("x", weight=2)
        assert core.register("x", weight=9) is lane
        assert lane.weight == 2
        core.close()

    def test_saturating_tenant_cannot_starve_light_queue_waits(self):
        """End-to-end fairness under a real flood: the light tenant's
        p50 queue wait stays within 3x of the per-flush baseline while
        the saturator queues deep and sheds."""
        async def go():
            prov = RecordingProvider(batch_cost_s=0.002)
            m = Metrics()
            core = SharedFrontier(prov, max_batch=32, linger_s=0.005,
                                  metrics=m)
            heavy = core.register("heavy", queue_bound=24)
            light = core.register("light", queue_bound=24)

            async def flood():
                for _ in range(6):
                    await asyncio.gather(
                        *(heavy.verify(b"s", b"h" * 16, b"HVY",
                                       msg_type="flood")
                          for _ in range(120)))

            async def trickle():
                oks = []
                for i in range(12):
                    oks.append(await light.verify(b"s", b"h" * 16,
                                                  b"L%03d" % i))
                    await asyncio.sleep(0.004)
                return oks

            _, oks = await asyncio.gather(flood(), trickle())
            assert all(oks)
            assert heavy.tenant_stats.sheds > 0
            assert light.tenant_stats.sheds == 0
            light_p50 = light.tenant_stats.p50_wait_ms()
            heavy_p50 = heavy.tenant_stats.p50_wait_ms()
            assert light_p50 is not None and heavy_p50 is not None
            # Baseline wait = linger (5 ms) + one flush (2 ms) + sched
            # slack; 3x that is the starvation bound.  The saturator
            # meanwhile queues 24 deep behind its own backlog.
            assert light_p50 <= 3 * (5.0 + 2.0 + 3.0), (light_p50,
                                                        heavy_p50)
            assert light_p50 <= heavy_p50
            core.close()
        run(go())


class TestPriorityLanes:
    def test_critical_drains_before_gossip_within_a_flush(self):
        """5 gossip enqueued BEFORE 3 critical: the composed batch still
        carries the tenant's critical entries first."""
        async def go():
            prov = RecordingProvider()
            core = SharedFrontier(prov, max_batch=8, linger_s=30.0)
            lane = core.register("t", queue_bound=100)
            gt = await enqueue(lane, [b"goss%d" % i for i in range(5)])
            ct = await enqueue(lane, [b"crit%d" % i for i in range(3)],
                               critical=True)
            # 8 pending == max_batch: the last enqueue flushed for real.
            for t in gt + ct:
                await t
            assert len(prov.batches) == 1
            voters = prov.batches[0]
            assert voters[:3] == [b"crit0", b"crit1", b"crit2"]
            assert sorted(voters[3:]) == [b"goss%d" % i for i in range(5)]
            core.close()
        run(go())

    def test_priority_toggle_off_restores_fifo(self):
        async def go():
            prov = RecordingProvider()
            core = SharedFrontier(prov, max_batch=4, linger_s=30.0)
            lane = core.register("t", queue_bound=100,
                                 priority_lanes=False)
            gt = await enqueue(lane, [b"g0", b"g1"])
            ct = await enqueue(lane, [b"c0", b"c1"], critical=True)
            for t in gt + ct:
                await t
            assert prov.batches == [[b"g0", b"g1", b"c0", b"c1"]]
            core.close()
        run(go())


class TestAdmissionControl:
    def test_overflow_sheds_exact_host_verdicts(self):
        """Arrivals over the bound verify on the host oracle with exact
        verdicts while the queued 8 wait for the (distant) linger."""
        async def go():
            prov = RecordingProvider()
            m = Metrics()
            core = SharedFrontier(prov, max_batch=1024, linger_s=30.0,
                                  metrics=m)
            lane = core.register("t", queue_bound=8)
            queued = await enqueue(lane, [b"Q%d" % i for i in range(8)])
            assert lane.pending_count() == 8
            # Over the bound: 2 good + 2 bad voters — shed, not queued.
            shed = [await lane.verify(b"s", b"h" * 16, v)
                    for v in (b"okA", b"bad1", b"okB", b"bad2")]
            assert shed == [True, False, True, False]
            assert lane.pending_count() == 8  # sheds never queued
            assert prov.host_verifies == [b"okA", b"bad1", b"okB", b"bad2"]
            assert lane.tenant_stats.sheds == 4
            assert lane.tenant_stats.failures == 2
            scraped = snapshot(m.registry)
            assert scraped[
                "frontier_admission_sheds_total{tenant=t}"] == 4.0
            core.close()  # shutdown flush resolves the queued 8
            assert all(await asyncio.gather(*queued))
        run(go())

    def test_stalled_device_bounds_batching_verifier_outstanding(self):
        """The unbounded-pending bugfix, in a VALID service config
        (max_pending >= max_batch): a wedged device drains the waiting
        queue into in-flight batches at every flush, so the bound
        counts OUTSTANDING work (waiting + unresolved) — arrivals past
        it shed to the host oracle instead of accumulating futures
        without limit."""
        import threading

        release = threading.Event()

        class WedgedProvider(RecordingProvider):
            def verify_batch(self, sigs, hashes, voters):
                release.wait(10.0)  # the stalled chip
                return super().verify_batch(sigs, hashes, voters)

        async def go():
            prov = WedgedProvider()
            m = Metrics()
            fr = BatchingVerifier(prov, max_batch=4, linger_s=30.0,
                                  metrics=m, max_pending=8)
            # 8 submits: two max_batch flushes wedge on the device —
            # waiting queue empty, 8 in flight, bound reached.
            inflight = await enqueue(fr, [b"Q%d" % i for i in range(8)])
            assert fr.pending_count() == 0
            assert fr.outstanding_count() == 8
            shed = await asyncio.gather(
                *(fr.verify(b"s", b"h" * 16, b"over%d" % i)
                  for i in range(4)))
            assert shed == [True] * 4
            assert fr.outstanding_count() == 8  # sheds never queued
            assert fr.tenant_stats.sheds == 4
            assert fr.stats.sheds == 4       # legacy stats see them too
            assert fr.stats.requests == 8    # ...but mean_batch doesn't
            scraped = snapshot(m.registry)
            assert scraped[
                "frontier_admission_sheds_total{tenant=default}"] == 4.0
            release.set()  # chip recovers; wedged batches resolve exact
            assert all(await asyncio.gather(*inflight))
            assert fr.outstanding_count() == 0
            fr.close()
        run(go())


class TestSingleLaneIdentity:
    """The refactor contract: BatchingVerifier behaves exactly as before
    for the classic single-engine path (test_frontier.py covers the
    original surface; these pin the refactor-specific seams)."""

    def test_is_a_lane_over_an_owned_core(self):
        prov = RecordingProvider()
        fr = BatchingVerifier(prov, max_batch=64, linger_s=0.01)
        assert fr.core.tenants == {"default": fr}
        assert fr.tenants_status()["default"]["queue_bound"] > 0
        fr.close()

    def test_legacy_stats_shape_and_coalescing(self):
        async def go():
            crypto = sim_crypto(b"\x07" * 32)
            h = sm3_hash(b"m")
            sig = crypto.sign(h)
            fr = BatchingVerifier(crypto, max_batch=64, linger_s=0.01)
            results = await asyncio.gather(
                *(fr.verify(sig, h, crypto.pub_key) for _ in range(20)))
            assert all(results)
            assert fr.stats.requests == 20 and fr.stats.batches == 1
            assert fr.stats.mean_batch == 20.0
            assert fr.tenant_stats.requests == 20
            assert fr.tenant_stats.sheds == 0
            fr.close()
        run(go())

    def test_proposal_rides_the_critical_lane(self):
        """verify_msg classifies SignedProposal as critical — visible in
        the tenant's critical_requests counter and p50 split."""
        async def go():
            from consensus_overlord_tpu.core.types import (
                Proposal, SignedProposal, SignedVote, Vote, VoteType)
            crypto = sim_crypto(b"\x09" * 32)
            fr = BatchingVerifier(crypto, max_batch=64, linger_s=0.005)
            p = Proposal(1, 0, b"c", sm3_hash(b"c"), None, crypto.pub_key)
            sp = SignedProposal(p, crypto.sign(sm3_hash(p.encode())))
            v = Vote(1, 0, VoteType.PREVOTE, sm3_hash(b"c"))
            sv = SignedVote(crypto.pub_key,
                            crypto.sign(sm3_hash(v.encode())), v)
            ok_p, ok_v = await asyncio.gather(fr.verify_msg(sp),
                                              fr.verify_msg(sv))
            assert ok_p and ok_v
            assert fr.tenant_stats.critical_requests == 1
            assert fr.tenant_stats.requests == 2
            fr.close()
        run(go())


class TestConfigKnobs:
    def test_defaults_validate_and_inherit(self):
        from consensus_overlord_tpu.service.config import ConsensusConfig
        cfg = ConsensusConfig()
        assert cfg.frontier_max_pending == 8192
        assert cfg.tenant_queue_bound == 0
        assert cfg.effective_tenant_queue_bound == 8192
        cfg2 = ConsensusConfig(tenant_queue_bound=2048)
        assert cfg2.effective_tenant_queue_bound == 2048

    def test_bad_values_raise(self):
        from consensus_overlord_tpu.service.config import ConsensusConfig
        with pytest.raises(ValueError):
            ConsensusConfig(tenant_weight=0)
        with pytest.raises(ValueError):
            ConsensusConfig(tenant_queue_bound=-1)
        with pytest.raises(ValueError):
            ConsensusConfig(frontier_max_pending=16)  # < max_batch
        with pytest.raises(ValueError):
            # nonzero override below max_batch: same degenerate state
            ConsensusConfig(tenant_queue_bound=16)
        with pytest.raises(ValueError):
            ConsensusConfig(frontier_max_batch=0)
        # a tight bound is fine when max_batch shrinks with it
        ConsensusConfig(frontier_max_batch=16, frontier_max_pending=16,
                        tenant_queue_bound=16)

    def test_lane_rejects_degenerate_knobs(self):
        prov = RecordingProvider()
        core = SharedFrontier(prov)
        with pytest.raises(ValueError):
            core.register("w0", weight=0)
        with pytest.raises(ValueError):
            core.register("q0", queue_bound=0)
        core.close()

    def test_single_tenant_bound_below_max_batch_rejected(self):
        """Direct constructions hit the same wall as the config layer:
        a single-tenant frontier bounded below one batch could never
        size-flush.  (Multi-tenant lanes MAY sit below the shared
        max_batch — batches compose across tenants.)"""
        prov = RecordingProvider()
        with pytest.raises(ValueError):
            BatchingVerifier(prov, max_batch=16384)  # default max_pending
        core = SharedFrontier(prov, max_batch=64)
        core.register("tight", queue_bound=48)  # fine for a shared lane
        core.close()


class TestChaosStall:
    def test_inject_stall_backs_up_then_sheds_exact_verdicts(self):
        """A stalled shared device (chaos tenant_stall) holds composed
        batches; arrivals past the bound shed to the host oracle with
        exact verdicts — flow control, never a drop or a wrong answer."""
        async def go():
            prov = RecordingProvider()
            core = SharedFrontier(prov, max_batch=8, linger_s=0.001)
            lane = core.register("t", queue_bound=8)
            core.inject_stall(0.15)
            assert core.stall_injected
            tasks = await enqueue(lane, [b"ok%d" % i for i in range(8)])
            # over the bound while the device sleeps: must shed, and a
            # bad signature must still come back False from the oracle
            shed_ok = await lane.verify(b"s", b"h" * 16, b"ok-shed")
            shed_bad = await lane.verify(b"s", b"h" * 16, b"bad-shed")
            assert shed_ok is True and shed_bad is False
            assert lane.tenant_stats.sheds == 2
            assert prov.host_verifies  # the oracle served the sheds
            results = await asyncio.gather(*tasks)
            assert all(results)  # the stalled batch resolved correctly
            core.close()
        run(go())


class TestSharedLaneRestart:
    def test_restart_node_reregisters_its_tenant_lane(self):
        """A crashed-and-restarted validator on a shared frontier_factory
        lane must land back in ITS OWN lane (register is idempotent by
        tenant id), the core must survive with every other tenant's
        stats intact, and the fleet must keep committing through the
        restarted node's lane."""
        from consensus_overlord_tpu.crypto.provider import SimHashCrypto
        from consensus_overlord_tpu.sim import SimNetwork

        async def go():
            m = Metrics()
            core = SharedFrontier(SimHashCrypto(b"\x44" * 32),
                                  max_batch=64, linger_s=0.002,
                                  metrics=m)
            factory = lambda crypto: core.register(  # noqa: E731
                "v-" + crypto.pub_key[:4].hex(), queue_bound=128)
            net = SimNetwork(
                n_validators=4, block_interval_ms=60,
                crypto_factory=lambda i: SimHashCrypto(
                    bytes([i + 1]) * 32),
                metrics=m, frontier_factory=factory,
                shared_frontier=core)
            assert len(core.tenants) == 4
            net.start(init_height=1)
            await net.run_until_height(2, timeout=30)
            lane_before = net.nodes[1].frontier
            requests_before = lane_before.tenant_stats.requests
            assert requests_before > 0  # the lane carried verify traffic
            net.crash_node(1)
            await asyncio.sleep(0.1)
            revived = net.restart_node(1)
            # same lane object, not a new tenant — stats continue
            assert revived.frontier is lane_before
            assert len(core.tenants) == 4
            await net.run_until_height(4, timeout=30)
            await net.stop()
            core.close()
            await asyncio.sleep(0.05)
            assert not net.controller.violations
            assert revived.frontier.tenant_stats.requests \
                > requests_before
            # the other tenants' lanes were untouched by the restart
            for i in (0, 2, 3):
                assert net.nodes[i].frontier.tenant_stats.requests > 0
        run(go())


class TestTenantStatus:
    def test_statusz_tenants_shape(self):
        async def go():
            prov = RecordingProvider()
            core = SharedFrontier(prov, max_batch=4, linger_s=0.005)
            a = core.register("a")
            core.register("b")
            assert await a.verify(b"s", b"h" * 16, b"ok")
            doc = core.tenants_status()
            assert set(doc) == {"a", "b"}
            for key in ("weight", "queue_bound", "queued", "requests",
                        "sheds", "failures", "lanes_contributed",
                        "p50_wait_ms", "p50_critical_wait_ms"):
                assert key in doc["a"], key
            assert doc["a"]["requests"] == 1
            assert doc["a"]["lanes_contributed"] == 1
            assert doc["b"]["requests"] == 0
            assert doc["b"]["p50_wait_ms"] is None
            core.close()
        run(go())
