"""graftlint (consensus_overlord_tpu/analysis): per-rule fixtures —
one true positive, one clean twin, one suppressed case each — plus the
whole-repo smoke run (the tree must lint clean), the baseline
round-trip, and the OBS001 doc-desync round-trip.

Everything here is stdlib + pytest: the analyzer itself never imports
jax, so these tests run in any lane.
"""

import json
import os
import subprocess
import sys
import unittest

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from consensus_overlord_tpu.analysis import (  # noqa: E402
    Project,
    run_rules,
)
from consensus_overlord_tpu.analysis.core import (  # noqa: E402
    load_baseline,
    write_baseline,
)
from consensus_overlord_tpu.analysis.rules_sim import (  # noqa: E402
    LEGACY_DRAWS,
    SENTINEL,
)


def lint_snippet(tmp_path, source, rules, filename="fixture.py",
                 **overrides):
    """Run the given rules over one fixture file; returns LintResult."""
    path = tmp_path / filename
    path.write_text(source)
    project = Project(str(tmp_path),
                      overrides={"files": [str(path)], **overrides})
    return run_rules(project, rules=rules)


def codes(result):
    return [f.rule for f in result.findings]


# ---------------------------------------------------------------------------
# TPU001 — host-sync ops inside jit
# ---------------------------------------------------------------------------

TPU001_BAD = """\
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def kernel(x):
    y = helper(x)
    print("tracing", y)
    return y

def helper(x):
    return np.asarray(x) + 1
"""

TPU001_CLEAN = """\
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def kernel(x):
    return jnp.asarray(x) + 1

def host_decode(out):
    # not reachable from the jitted entry: host-side sync is fine here
    return np.asarray(jax.device_get(out))
"""

TPU001_SUPPRESSED = """\
import jax

@jax.jit
def kernel(x):
    print(x)  # graftlint: disable=TPU001 -- trace-time debug marker
    return x
"""


class TestTPU001(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def test_true_positive(self):
        result = lint_snippet(self.tmp, TPU001_BAD, ["TPU001"])
        self.assertEqual(set(codes(result)), {"TPU001"})
        # both the direct print and the np.asarray in the reachable
        # helper are flagged
        self.assertEqual(len(result.findings), 2)

    def test_clean_twin(self):
        result = lint_snippet(self.tmp, TPU001_CLEAN, ["TPU001"])
        self.assertEqual(codes(result), [])

    def test_suppressed(self):
        result = lint_snippet(self.tmp, TPU001_SUPPRESSED, ["TPU001"])
        self.assertEqual(codes(result), [])
        self.assertEqual(len(result.suppressed), 1)


# ---------------------------------------------------------------------------
# TPU002 — int32-limb upcast hazards
# ---------------------------------------------------------------------------

TPU002_BAD = """\
import jax.numpy as jnp

def widen(x):
    y = x.astype(jnp.int64)
    z = jnp.einsum("ij,jk->ik", y, y)
    return z * 3000000000
"""

TPU002_CLEAN = """\
import jax.numpy as jnp

_I32_MAX = 2**31 - 1  # pure-literal math folds at trace time

def _reduce(x, fold):
    return jnp.einsum("ij,jk->ik", x, fold)

def narrow(x):
    return _reduce(x.astype(jnp.int32), x) * 3
"""

TPU002_SUPPRESSED = """\
import jax.numpy as jnp

def widen(x):
    # graftlint: disable=TPU002 -- documented one-off host staging copy
    return x.astype(jnp.int64)
"""


class TestTPU002(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def test_true_positive(self):
        result = lint_snippet(self.tmp, TPU002_BAD, ["TPU002"])
        self.assertEqual(set(codes(result)), {"TPU002"})
        # astype(int64) + einsum outside the guard + the big literal
        self.assertEqual(len(result.findings), 3)

    def test_clean_twin(self):
        result = lint_snippet(self.tmp, TPU002_CLEAN, ["TPU002"])
        self.assertEqual(codes(result), [])

    def test_suppressed(self):
        result = lint_snippet(self.tmp, TPU002_SUPPRESSED, ["TPU002"])
        self.assertEqual(codes(result), [])
        self.assertEqual(len(result.suppressed), 1)


# ---------------------------------------------------------------------------
# TPU003 — recompile hazards
# ---------------------------------------------------------------------------

TPU003_BAD = """\
import jax

@jax.jit
def kernel(x, mode="fast"):
    return x
"""

TPU003_CLEAN = """\
from functools import partial

import jax

@partial(jax.jit, static_argnames=("mode",))
def kernel(x, mode="fast"):
    return x

@jax.jit
def plain(x, scale=None):
    return x
"""

TPU003_SUPPRESSED = """\
import jax

# graftlint: disable=TPU003 -- mode is only ever passed one value
@jax.jit
def kernel(x, mode="fast"):
    return x
"""


class TestTPU003(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def test_true_positive(self):
        result = lint_snippet(self.tmp, TPU003_BAD, ["TPU003"])
        self.assertEqual(codes(result), ["TPU003"])

    def test_clean_twin(self):
        result = lint_snippet(self.tmp, TPU003_CLEAN, ["TPU003"])
        self.assertEqual(codes(result), [])

    def test_suppressed(self):
        result = lint_snippet(self.tmp, TPU003_SUPPRESSED, ["TPU003"])
        self.assertEqual(codes(result), [])
        self.assertEqual(len(result.suppressed), 1)


# ---------------------------------------------------------------------------
# CONC001 — lock discipline
# ---------------------------------------------------------------------------

CONC001_BAD = """\
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def bump(self):
        with self._lock:
            self.total += 1

    def reset(self):
        self.total = 0  # race: written elsewhere under the lock
"""

CONC001_CLEAN = """\
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def bump(self):
        with self._lock:
            self.total += 1

    def reset(self):
        with self._lock:
            self._reset_locked()

    def _reset_locked(self):
        # "caller holds the lock" helper: every call site is locked
        self.total = 0
"""

CONC001_SUPPRESSED = """\
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def bump(self):
        with self._lock:
            self.total += 1

    def reset(self):
        self.total = 0  # graftlint: disable=CONC001 -- single-threaded teardown
"""


class TestCONC001(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def test_true_positive(self):
        result = lint_snippet(self.tmp, CONC001_BAD, ["CONC001"])
        self.assertEqual(codes(result), ["CONC001"])
        self.assertIn("total", result.findings[0].message)

    def test_clean_twin(self):
        result = lint_snippet(self.tmp, CONC001_CLEAN, ["CONC001"])
        self.assertEqual(codes(result), [])

    def test_suppressed(self):
        result = lint_snippet(self.tmp, CONC001_SUPPRESSED, ["CONC001"])
        self.assertEqual(codes(result), [])
        self.assertEqual(len(result.suppressed), 1)


# ---------------------------------------------------------------------------
# CONC002 — device-path failure containment
# ---------------------------------------------------------------------------

CONC002_BAD = """\
import jax

@jax.jit
def kernel(x):
    return x

class Provider:
    def verify(self, x):
        try:
            out = kernel(x)
            return jax.device_get(out)
        except Exception:
            return None  # swallowed: no breaker, fallback, or log

    def dispatch_uncontained(self, x):
        return kernel(x)  # no try at all
"""

CONC002_CLEAN = """\
import logging

import jax

logger = logging.getLogger(__name__)

@jax.jit
def kernel(x):
    return x

class Provider:
    def verify(self, x):
        try:
            out = kernel(x)
            return jax.device_get(out)
        except Exception as e:
            logger.warning("device failed: %s; host fallback", e)
            return self.verify_signature(x)

    def verify_signature(self, x):
        return True
"""

CONC002_SUPPRESSED = """\
import jax

@jax.jit
def kernel(x):
    return x

class Provider:
    def probe(self, x):
        try:
            jax.device_get(kernel(x))
        # graftlint: disable=CONC002 -- best-effort probe, result unused
        except Exception:
            pass
"""


class TestCONC002(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def test_true_positive(self):
        result = lint_snippet(self.tmp, CONC002_BAD, ["CONC002"])
        self.assertEqual(set(codes(result)), {"CONC002"})
        # the swallowing handler + the uncontained dispatch
        self.assertEqual(len(result.findings), 2)

    def test_clean_twin(self):
        result = lint_snippet(self.tmp, CONC002_CLEAN, ["CONC002"])
        self.assertEqual(codes(result), [])

    def test_suppressed(self):
        result = lint_snippet(self.tmp, CONC002_SUPPRESSED, ["CONC002"])
        self.assertEqual(codes(result), [])
        self.assertEqual(len(result.suppressed), 1)

    def test_retry_in_handler_is_uncontained(self):
        """A dispatch inside an except block is NOT protected by the
        try it handles — its failure escapes that try entirely."""
        src = ("import logging\n\nimport jax\n\n"
               "logger = logging.getLogger(__name__)\n\n"
               "@jax.jit\ndef kernel(x):\n    return x\n\n"
               "class Provider:\n"
               "    def verify(self, x):\n"
               "        try:\n"
               "            return kernel(x)\n"
               "        except Exception as e:\n"
               "            logger.warning('retrying: %s', e)\n"
               "            return kernel(x)\n")
        result = lint_snippet(self.tmp, src, ["CONC002"])
        self.assertEqual(codes(result), ["CONC002"])
        self.assertIn("not inside any try", result.findings[0].message)
        # a nested try around the retry contains it again
        contained = src.replace(
            "            logger.warning('retrying: %s', e)\n"
            "            return kernel(x)\n",
            "            logger.warning('retrying: %s', e)\n"
            "            try:\n"
            "                return kernel(x)\n"
            "            except Exception:\n"
            "                logger.error('gave up')\n"
            "                return None\n")
        result2 = lint_snippet(self.tmp, contained, ["CONC002"],
                               filename="contained.py")
        self.assertEqual(codes(result2), [])


# ---------------------------------------------------------------------------
# OBS001 — metric & statusz contract (fixture round-trip)
# ---------------------------------------------------------------------------

OBS_METRICS_SRC = """\
from prometheus_client import Counter, Gauge, Histogram

class Metrics:
    def __init__(self):
        self.verifies = Counter(
            "crypto_verifies_total", "verifies", registry=None)
        self.wait = Histogram(
            "queue_wait_ms", "wait", registry=None)
"""

OBS_README_SRC = """\
# obs

## Metric families

| family | type | labels | meaning |
|---|---|---|---|
| `crypto_verifies_total` | counter | — | verifies |
| `queue_wait_ms` | histogram | — | wait |

## /statusz

Schema as wired by service/main.py:

```json
{
  "ts": 0.0,
  "consensus": {},
  "frontier": {}
}
```
"""

OBS_MAIN_SRC = """\
class Service:
    def wire(self, metrics, engine, frontier):
        metrics.add_status_source("consensus", engine.status)
        metrics.add_status_source("frontier", frontier.status)
"""

OBS_USER_SRC = """\
def observe(metrics):
    metrics.verifies.inc()
    metrics.wait.observe(1.0)
"""


def obs_project(tmp_path, metrics=OBS_METRICS_SRC, readme=OBS_README_SRC,
                main=OBS_MAIN_SRC, user=OBS_USER_SRC):
    (tmp_path / "metrics.py").write_text(metrics)
    (tmp_path / "README.md").write_text(readme)
    (tmp_path / "main.py").write_text(main)
    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "user.py").write_text(user)
    return Project(str(tmp_path), overrides={
        "obs_metrics": "metrics.py",
        "obs_readme": "README.md",
        "service_main": "main.py",
        "search_roots": ("pkg",),
    })


class TestOBS001(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def test_in_sync_is_clean(self):
        result = run_rules(obs_project(self.tmp), rules=["OBS001"])
        self.assertEqual(codes(result), [])

    def test_registered_but_undocumented(self):
        readme = OBS_README_SRC.replace(
            "| `queue_wait_ms` | histogram | — | wait |\n", "")
        result = run_rules(obs_project(self.tmp, readme=readme),
                           rules=["OBS001"])
        self.assertEqual(codes(result), ["OBS001"])
        self.assertIn("queue_wait_ms", result.findings[0].message)
        self.assertIn("missing", result.findings[0].message)

    def test_documented_but_unregistered(self):
        # desync the other way: rename the registered family so the
        # README row goes stale — OBS001 must flag the README side too
        metrics = OBS_METRICS_SRC.replace("queue_wait_ms",
                                          "queue_delay_ms")
        user = OBS_USER_SRC  # attr names unchanged
        result = run_rules(obs_project(self.tmp, metrics=metrics,
                                       user=user), rules=["OBS001"])
        found = {(f.rule, f.path.split("/")[-1]) for f in result.findings}
        self.assertIn(("OBS001", "README.md"), found)   # stale row
        self.assertIn(("OBS001", "metrics.py"), found)  # new name undoc'd

    def test_dead_family(self):
        user = "def observe(metrics):\n    metrics.verifies.inc()\n"
        result = run_rules(obs_project(self.tmp, user=user),
                           rules=["OBS001"])
        self.assertEqual(codes(result), ["OBS001"])
        self.assertIn("never referenced", result.findings[0].message)

    def test_statusz_desync(self):
        main = OBS_MAIN_SRC + (
            "        metrics.add_status_source(\"trend\", lambda: {})\n")
        result = run_rules(obs_project(self.tmp, main=main),
                           rules=["OBS001"])
        self.assertEqual(codes(result), ["OBS001"])
        self.assertIn("trend", result.findings[0].message)

    def test_suppressed(self):
        readme = OBS_README_SRC.replace(
            "| `queue_wait_ms` | histogram | — | wait |\n", "")
        metrics = OBS_METRICS_SRC.replace(
            "        self.wait = Histogram(",
            "        # graftlint: disable=OBS001 -- internal-only family\n"
            "        self.wait = Histogram(")
        result = run_rules(obs_project(self.tmp, metrics=metrics,
                                       readme=readme), rules=["OBS001"])
        self.assertEqual(codes(result), [])
        self.assertEqual(len(result.suppressed), 1)


# ---------------------------------------------------------------------------
# SIM001 — append-only RNG draw order
# ---------------------------------------------------------------------------

def sim_chaos_src(extra_legacy_draw=False, sentinel=True,
                  suppress=False):
    lines = [
        "import random",
        "",
        "class ChaosSchedule:",
        "    @classmethod",
        "    def generate(cls, seed, heights, n_validators,",
        "                 adaptive=0):",
        "        rng = random.Random(seed)",
        "        slots = rng.sample(range(heights), 3)",
        "        kinds = rng.choice(['crash'])",
        "        rng.shuffle(slots)",
        "        targets = rng.sample(range(n_validators), 2)",
        "        node = rng.randrange(n_validators)",
    ]
    if extra_legacy_draw:
        line = "        jitter = rng.random()"
        if suppress:
            line += ("  # graftlint: disable=SIM001 -- fixture: "
                     "intentionally accepted draw")
        lines.append(line)
    if sentinel:
        lines.append(f"        # {SENTINEL}")
    lines.append("        extras = [rng.choice(slots)"
                 " for _ in range(adaptive)]")
    lines.append("        return (slots, kinds, targets, node, extras)")
    return "\n".join(lines) + "\n"


class TestSIM001(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def run_sim(self, src):
        path = self.tmp / "chaos.py"
        path.write_text(src)
        project = Project(str(self.tmp),
                          overrides={"sim_chaos": "chaos.py"})
        return run_rules(project, rules=["SIM001"])

    def test_clean_twin(self):
        self.assertEqual(codes(self.run_sim(sim_chaos_src())), [])

    def test_inserted_draw_above_sentinel(self):
        result = self.run_sim(sim_chaos_src(extra_legacy_draw=True))
        self.assertEqual(codes(result), ["SIM001"])
        self.assertIn("re-seeds every recorded", result.findings[0].message)

    def test_missing_sentinel(self):
        result = self.run_sim(sim_chaos_src(sentinel=False))
        self.assertEqual(codes(result), ["SIM001"])
        self.assertIn("sentinel", result.findings[0].message)

    def test_suppressed(self):
        result = self.run_sim(sim_chaos_src(extra_legacy_draw=True,
                                            suppress=True))
        self.assertEqual(codes(result), [])
        self.assertEqual(len(result.suppressed), 1)

    def test_pinned_sequence_matches_real_generator(self):
        """The pin in rules_sim must describe the REAL sim/chaos.py —
        if this fails, generate() changed its legacy draw block."""
        project = Project(REPO_ROOT)
        result = run_rules(project, rules=["SIM001"])
        self.assertEqual(codes(result), [],
                         msg="sim/chaos.py legacy draws drifted from "
                             f"LEGACY_DRAWS={LEGACY_DRAWS}")


# ---------------------------------------------------------------------------
# suppression + baseline machinery
# ---------------------------------------------------------------------------

class TestSuppressionSyntax(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def test_reasonless_suppression_is_gl001(self):
        src = ("import jax\n\n@jax.jit\ndef kernel(x):\n"
               "    print(x)  # graftlint: disable=TPU001\n"
               "    return x\n")
        result = lint_snippet(self.tmp, src, ["TPU001"])
        self.assertEqual(set(codes(result)), {"GL001", "TPU001"})

    def test_wrong_rule_does_not_suppress(self):
        src = ("import jax\n\n@jax.jit\ndef kernel(x):\n"
               "    print(x)  # graftlint: disable=TPU002 -- wrong code\n"
               "    return x\n")
        result = lint_snippet(self.tmp, src, ["TPU001"])
        self.assertEqual(codes(result), ["TPU001"])

    def test_stale_suppression_is_gl003(self):
        # the suppressed violation was fixed but the comment stayed:
        # its rule ran and absorbed nothing -> flag the dead comment
        src = ("import jax\n\n@jax.jit\ndef kernel(x):\n"
               "    return x  # graftlint: disable=TPU001 -- stale\n")
        result = lint_snippet(self.tmp, src, ["TPU001"])
        self.assertEqual(codes(result), ["GL003"])

    def test_unselected_rule_suppression_not_stale(self):
        # CONC002 didn't run: its suppression can't be judged stale
        src = ("import jax\n\n@jax.jit\ndef kernel(x):\n"
               "    return x  # graftlint: disable=CONC002 -- other\n")
        result = lint_snippet(self.tmp, src, ["TPU001"])
        self.assertEqual(codes(result), [])


class TestBaseline(unittest.TestCase):
    @pytest.fixture(autouse=True)
    def _tmp(self, tmp_path):
        self.tmp = tmp_path

    def _one_finding(self):
        path = self.tmp / "fixture.py"
        path.write_text(TPU003_BAD)
        return Project(str(self.tmp), overrides={"files": [str(path)]})

    def test_round_trip(self):
        project = self._one_finding()
        result = run_rules(project, rules=["TPU003"])
        self.assertEqual(codes(result), ["TPU003"])

        baseline = self.tmp / "baseline.json"
        write_baseline(str(baseline), result.findings)
        # skeleton entries have empty reasons: still red, now as GL002
        result2 = run_rules(self._one_finding(), rules=["TPU003"],
                            baseline_path=str(baseline))
        self.assertIn("GL002", codes(result2))

        doc = json.loads(baseline.read_text())
        for entry in doc["entries"]:
            entry["reason"] = "accepted: fixture for the baseline test"
        baseline.write_text(json.dumps(doc))
        result3 = run_rules(self._one_finding(), rules=["TPU003"],
                            baseline_path=str(baseline))
        self.assertEqual(codes(result3), [])
        self.assertEqual(len(result3.baselined), 1)
        self.assertEqual(result3.exit_code, 0)

    def test_fingerprint_survives_line_drift(self):
        project = self._one_finding()
        result = run_rules(project, rules=["TPU003"])
        baseline = self.tmp / "baseline.json"
        write_baseline(str(baseline), result.findings)
        doc = json.loads(baseline.read_text())
        for entry in doc["entries"]:
            entry["reason"] = "accepted"
        baseline.write_text(json.dumps(doc))
        # shift the finding down three lines: fingerprint still matches
        (self.tmp / "fixture.py").write_text("# pad\n# pad\n# pad\n"
                                             + TPU003_BAD)
        result2 = run_rules(self._one_finding(), rules=["TPU003"],
                            baseline_path=str(baseline))
        self.assertEqual(codes(result2), [])
        self.assertEqual(len(result2.baselined), 1)

    def test_duplicate_line_gets_distinct_fingerprint(self):
        """A baseline entry accepts exactly ONE occurrence: a new
        copy-paste of the identical violating line must still fail."""
        two = ("import jax\n\n@jax.jit\ndef kernel(x):\n"
               "    print(x)\n    print(x)\n    return x\n")
        path = self.tmp / "fixture.py"
        path.write_text(two)

        def proj():
            return Project(str(self.tmp),
                           overrides={"files": [str(path)]})

        result = run_rules(proj(), rules=["TPU001"])
        self.assertEqual(len(result.findings), 2)
        self.assertNotEqual(result.findings[0].fingerprint,
                            result.findings[1].fingerprint)
        baseline = self.tmp / "baseline.json"
        write_baseline(str(baseline), result.findings)
        doc = json.loads(baseline.read_text())
        for entry in doc["entries"]:
            entry["reason"] = "accepted pair"
        baseline.write_text(json.dumps(doc))
        result2 = run_rules(proj(), rules=["TPU001"],
                            baseline_path=str(baseline))
        self.assertEqual(codes(result2), [])
        # a third identical line is NEW work, not covered by the pair
        path.write_text(two.replace("    return x\n",
                                    "    print(x)\n    return x\n"))
        result3 = run_rules(proj(), rules=["TPU001"],
                            baseline_path=str(baseline))
        self.assertEqual(codes(result3), ["TPU001"])
        self.assertEqual(len(result3.baselined), 2)

    def test_missing_baseline_file(self):
        fps, findings = load_baseline(str(self.tmp / "nope.json"))
        self.assertEqual(fps, {})
        self.assertEqual([f.rule for f in findings], ["GL002"])


# ---------------------------------------------------------------------------
# whole-repo smoke + CLI
# ---------------------------------------------------------------------------

class TestWholeRepo(unittest.TestCase):
    def test_repo_lints_clean(self):
        """The committed tree must carry zero actionable findings — the
        same bar the check.yml lint-invariants job enforces."""
        result = run_rules(Project(REPO_ROOT))
        self.assertEqual(
            [f.render() for f in result.findings], [],
            msg="the tree must lint clean (fix or suppress with a "
                "reason / baseline entry)")

    def test_cli_json_exit0(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                          "graftlint.py"), "--json"],
            capture_output=True, text=True, cwd=REPO_ROOT)
        self.assertEqual(proc.returncode, 0, msg=proc.stdout + proc.stderr)
        doc = json.loads(proc.stdout)
        self.assertEqual(doc["findings"], [])
        self.assertEqual(doc["exit_code"], 0)

    def test_cli_nonzero_with_rule_code(self):
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            fixture = os.path.join(tmp, "bad.py")
            with open(fixture, "w") as f:
                f.write(TPU003_BAD)
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(REPO_ROOT, "scripts", "graftlint.py"),
                 "--rules", "TPU003", "--json", fixture],
                capture_output=True, text=True, cwd=REPO_ROOT)
            self.assertEqual(proc.returncode, 1)
            doc = json.loads(proc.stdout)
            self.assertEqual([f["rule"] for f in doc["findings"]],
                             ["TPU003"])

    def test_list_rules(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                          "graftlint.py"),
             "--list-rules"],
            capture_output=True, text=True, cwd=REPO_ROOT)
        self.assertEqual(proc.returncode, 0)
        listed = set(proc.stdout.split())
        for code in ("TPU001", "TPU002", "TPU003", "CONC001", "CONC002",
                     "OBS001", "SIM001"):
            self.assertIn(code, listed)


if __name__ == "__main__":
    unittest.main()
