"""RFC 9380 hash-to-G1 (SSWU + derived 11-isogeny) known-answer tests.

The isogeny coefficients in crypto/hash_to_curve.py are machine-derived
(scripts/derive_g1_isogeny.py); these vectors — RFC 9380 Appendix J.9.1
(BLS12381G1_XMD:SHA-256_SSWU_RO_) and K.1 (expand_message_xmd SHA-256)
— pin them to the standard byte-for-byte."""

from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.crypto.hash_to_curve import (
    expand_message_xmd, hash_to_curve_g1, map_to_curve_sswu, iso_map)

DST = b"QUUX-V01-CS02-with-BLS12381G1_XMD:SHA-256_SSWU_RO_"

# (msg, P.x, P.y) from RFC 9380 J.9.1
VECTORS = [
    (b"",
     "052926add2207b76ca4fa57a8734416c8dc95e24501772c814278700eed6d1e4"
     "e8cf62d9c09db0fac349612b759e79a1",
     "08ba738453bfed09cb546dbb0783dbb3a5f1f566ed67bb6be0e8c67e2e81a4cc"
     "68ee29813bb7994998f3eae0c9c6a265"),
    (b"abc",
     "03567bc5ef9c690c2ab2ecdf6a96ef1c139cc0b2f284dca0a9a7943388a49a3a"
     "ee664ba5379a7655d3c68900be2f6903",
     "0b9c15f3fe6e5cf4211f346271d7b01c8f3b28be689c8429c85b67af21553331"
     "1f0b8dfaaa154fa6b88176c229f2885d"),
    (b"abcdef0123456789",
     "11e0b079dea29a68f0383ee94fed1b940995272407e3bb916bbf268c263ddd57"
     "a6a27200a784cbc248e84f357ce82d98",
     "03a87ae2caf14e8ee52e51fa2ed8eefe80f02457004ba4d486d6aa1f517c0889"
     "501dc7413753f9599b099ebcbbd2d709"),
    (b"q128_" + b"q" * 128,
     "15f68eaa693b95ccb85215dc65fa81038d69629f70aeee0d0f677cf22285e7bf"
     "58d7cb86eefe8f2e9bc3f8cb84fac488",
     "1807a1d50c29f430b8cafc4f8638dfeeadf51211e1602a5f184443076715f91b"
     "b90a48ba1e370edce6ae1062f5e6dd38"),
    (b"a512_" + b"a" * 512,
     "082aabae8b7dedb0e78aeb619ad3bfd9277a2f77ba7fad20ef6aabdc6c31d19b"
     "a5a6d12283553294c1825c4b3ca2dcfe",
     "05b84ae5a942248eea39e1d91030458c40153f3b654ab7872d779ad1e942856a"
     "20c438e8d99bc8abfbf74729ce1f7ac8"),
]


class TestExpandMessageXmd:
    """RFC 9380 K.1 (SHA-256, DST "QUUX-V01-CS02-with-expander-SHA256-128")."""

    DST = b"QUUX-V01-CS02-with-expander-SHA256-128"

    def test_len32(self):
        assert expand_message_xmd(b"", self.DST, 0x20).hex() == (
            "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d8"
            "03f07235")
        assert expand_message_xmd(b"abc", self.DST, 0x20).hex() == (
            "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a"
            "0d605615")

    def test_len128(self):
        out = expand_message_xmd(b"", self.DST, 0x80)
        assert len(out) == 0x80
        assert out.hex().startswith("af84c27ccfd45d41914fdff5df25293e")


class TestHashToCurveG1:
    def test_rfc_vectors(self):
        for msg, ex, ey in VECTORS:
            x, y = hash_to_curve_g1(msg, DST)
            assert f"{x:096x}" == ex, msg
            assert f"{y:096x}" == ey, msg

    def test_output_in_subgroup(self):
        for msg in (b"", b"vote-hash", b"\x00" * 32):
            pt = hash_to_curve_g1(msg, DST)
            assert oracle.g1_in_subgroup(pt)

    def test_sswu_lands_on_isogenous_curve(self):
        from consensus_overlord_tpu.crypto.hash_to_curve import (
            ISO_A, ISO_B, P)
        for u in (0, 1, 5, P - 2):
            x, y = map_to_curve_sswu(u)
            assert y * y % P == (pow(x, 3, P) + ISO_A * x + ISO_B) % P

    def test_iso_map_lands_on_e(self):
        from consensus_overlord_tpu.crypto.hash_to_curve import P
        pt = iso_map(map_to_curve_sswu(7))
        x, y = pt
        assert y * y % P == (pow(x, 3, P) + 4) % P


class TestSchemeIntegration:
    """hash_to_g1 (now SSWU by default) keeps the sign/verify scheme
    sound, and the legacy try-and-increment map stays available as a
    distinct cross-check."""

    def test_sign_verify_roundtrip_sswu(self):
        h = oracle.sm3_hash(b"block")
        sig = oracle.sign(0xABCD, h)
        assert oracle.verify(oracle.sk_to_pk(0xABCD), h, sig)
        assert not oracle.verify(oracle.sk_to_pk(0xABCD),
                                 oracle.sm3_hash(b"other"), sig)

    def test_legacy_map_differs_but_scheme_equivalent(self):
        h = oracle.sm3_hash(b"block")
        assert oracle.hash_to_g1(h) != oracle.hash_to_g1_try_increment(h)
        assert oracle.g1_in_subgroup(oracle.hash_to_g1_try_increment(h))
