"""Pre-compile the TPU provider kernel set for the pad-ladder rungs a
deployment will hit, so cold-start consensus rounds don't stall on XLA
compiles (a fresh kernel at a new batch rung can cost minutes; the
persistent cache under .jax_cache makes this a one-time cost per
machine).

Usage: python scripts/warm_cache.py [rung ...]   (default: 32 128 512)

Warms, per rung R: single-hash fused verify (pad R), 2- and 4-group
fused multi-hash verify, QC pubkey aggregation (g2_sum_rows), signature
aggregation (g1_validate_sum), and pubkey validation (g2_validate).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main() -> None:
    from consensus_overlord_tpu.compile_cache import enable
    enable()

    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto import bls12381 as oracle
    from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto

    rungs = [int(a) for a in sys.argv[1:]] or [32, 128, 512]
    provider = TpuBlsCrypto(0xFACE, device_threshold=1)
    top = max(rungs)
    sks = [4242 + 31 * i for i in range(top)]
    hs = [sm3_hash(b"warm-%d" % g) for g in range(4)]
    sigs = {h: [oracle.sign(sk, h) for sk in sks] for h in hs}
    pks = [oracle.sk_to_pk(sk) for sk in sks]
    provider.update_pubkeys(pks)  # g2_validate at the pubkey rung

    for rung in rungs:
        n = rung  # exact rung size (pads to itself)
        t0 = time.time()
        assert all(provider.verify_batch(sigs[hs[0]][:n], [hs[0]] * n,
                                         pks[:n]))
        print(f"rung {rung}: single-hash {time.time() - t0:.1f}s",
              flush=True)
        for k in (2, 4):
            t0 = time.time()
            lane_h = [hs[i % k] for i in range(n)]
            batch = [sigs[lane_h[i]][i] for i in range(n)]
            assert all(provider.verify_batch(batch, lane_h, pks[:n]))
            print(f"rung {rung}: {k}-hash {time.time() - t0:.1f}s",
                  flush=True)
        t0 = time.time()
        agg = provider.aggregate_signatures(sigs[hs[0]][:n], pks[:n])
        assert provider.verify_aggregated_signature(agg, hs[0], pks[:n])
        print(f"rung {rung}: aggregate+QC {time.time() - t0:.1f}s",
              flush=True)


if __name__ == "__main__":
    main()
