"""Pre-compile the TPU provider kernel set for the pad-ladder rungs a
deployment will hit, so cold-start consensus rounds don't stall on XLA
compiles (a fresh kernel at a new batch rung can cost minutes; the
persistent cache under .jax_cache makes this a one-time cost per
machine).

Usage: python scripts/warm_cache.py [rung ...]   (default: 32 128 512)

Warms, per rung R: single-hash fused verify (pad R), 2- and 4-group
fused multi-hash verify, QC pubkey aggregation (g2_sum_rows), signature
aggregation (g1_validate_sum), and pubkey validation (g2_validate).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main() -> None:
    from consensus_overlord_tpu.compile_cache import enable
    enable()

    from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
    from consensus_overlord_tpu.crypto.warm import warm_bls

    rungs = [int(a) for a in sys.argv[1:]] or [32, 128, 512]
    provider = TpuBlsCrypto(0xFACE, device_threshold=1)
    for rung in rungs:
        t0 = time.time()
        warm_bls(provider, [rung])
        print(f"rung {rung}: warmed in {time.time() - t0:.1f}s",
              flush=True)


if __name__ == "__main__":
    main()
