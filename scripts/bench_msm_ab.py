"""A/B the MSM formulations on the real device — the experiment behind
the r3 ledger entry in BASELINE.md ("digit-plane MSM measured 2.1x
slower than the windowed ladder and reverted").

The digit-plane (Pippenger-style) formulation lives HERE, not in
production code: ops/curve.py msm_bits is the ladder+tree form the
measurement selected.  Keeping the loser reproducible stops it being
re-tried blindly.

Measurement honesty (see BASELINE.md r3 ledger): the remote PJRT relay
dedupes repeated identical computations and block_until_ready is not a
reliable barrier through it — so every timed iteration here draws FRESH
random scalars and synchronizes via jax.device_get of a strict affine
output.  Identical result digests across formulations double as a
correctness cross-check.

Usage: python scripts/bench_msm_ab.py [N]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 8192


def digit_plane_msm(curve, p, bits):
    """Σᵢ kᵢ·pᵢ via signed base-16 digit planes: recode, one gathered
    table lookup per window, one batched tree reduction per window
    (window axis rides along the lane tree), width-1 Horner combine.
    ~4x fewer nominal point-ops/lane than the ladder — and measured
    2.1x slower on TPU v5e, which is why production msm_bits is the
    ladder."""
    import jax.numpy as jnp
    from jax import lax

    from consensus_overlord_tpu.ops.curve import Point

    nbits = bits.shape[-1]
    w0 = nbits // 4
    weights = jnp.asarray([8, 4, 2, 1], jnp.int32)
    vals = (bits.reshape(bits.shape[:-1] + (w0, 4)) * weights).sum(-1)
    vals_lsb = jnp.moveaxis(jnp.flip(vals, axis=-1), -1, 0)  # (w0, B)

    def recode(carry, v):
        t = v + carry
        over = t > 8
        return over.astype(jnp.int32), jnp.where(over, t - 16, t)

    carry, digs = lax.scan(
        recode, jnp.zeros(bits.shape[:-1], jnp.int32), vals_lsb)
    digs = jnp.concatenate([digs, carry[None]], axis=0)  # (W, B) LSB-first

    table = curve._signed_table(p)  # (9, B) points
    absd = jnp.abs(digs)
    lanes = jnp.arange(digs.shape[1])[None, :]
    sx = table.x[absd, lanes]  # (W, B, coord)
    sy = curve.f.where(digs < 0, curve.f.neg(table.y[absd, lanes]),
                       table.y[absd, lanes])
    sz = table.z[absd, lanes]
    sp = Point(jnp.moveaxis(sx, 0, 1), jnp.moveaxis(sy, 0, 1),
               jnp.moveaxis(sz, 0, 1))  # (B, W)
    red = curve.tree_sum(sp)  # (1, W)
    sw = Point(red.x[0], red.y[0], red.z[0])  # (W,) LSB-first

    def horner(acc, s):
        for _ in range(4):
            acc = curve.dbl(acc)
        return curve.add(acc, s), None

    acc, _ = lax.scan(
        horner, curve.infinity_like(sw.x[0]),
        Point(jnp.flip(sw.x, 0), jnp.flip(sw.y, 0), jnp.flip(sw.z, 0)))
    return Point(acc.x[None], acc.y[None], acc.z[None])


def time_honest(label, fn, fresh_bits, iters=3):
    """Fresh inputs per iteration + device_get barrier; prints per-run
    ms and the result digest (must match across formulations)."""
    jax.device_get(fn(fresh_bits()))  # warm/compile
    best = None
    for _ in range(iters):
        bits = fresh_bits()
        jax.block_until_ready(bits)
        t0 = time.perf_counter()
        out = jax.device_get(fn(bits))
        dt = (time.perf_counter() - t0) * 1e3
        best = dt if best is None or dt < best else best
        print(f"{label:16s} {dt:9.2f} ms  digest={int(np.asarray(out).sum())}",
              flush=True)
    return best


def main():
    from consensus_overlord_tpu.compile_cache import enable
    enable()
    import jax.numpy as jnp

    from consensus_overlord_tpu.ops import bls12381_groups as dev

    print(f"device: {jax.devices()[0].platform}  N={N}", flush=True)
    import bench
    bench.N = N
    sigs, h, pks = bench._fixture()

    rng = np.random.default_rng(7)

    def fresh_bits():
        return jnp.asarray(rng.integers(0, 2, (N, 64), dtype=np.int32))

    pk_parsed = dev.parse_g2_compressed(pks)
    g2pt, _ = jax.jit(dev.g2_decompress_device)(
        jnp.asarray(pk_parsed.x), jnp.asarray(pk_parsed.sign),
        jnp.asarray(pk_parsed.infinity), jnp.asarray(pk_parsed.wellformed))
    g2pt = jax.block_until_ready(g2pt)
    parsed = dev.parse_g1_compressed(sigs)
    g1pt, _ = jax.jit(dev.g1_decompress_device)(
        jnp.asarray(parsed.x), jnp.asarray(parsed.sign),
        jnp.asarray(parsed.infinity), jnp.asarray(parsed.wellformed))
    g1pt = jax.block_until_ready(g1pt)

    def strict_x(curve, p):
        return dev.FQ.strict(curve.to_affine(p)[0][0])

    results = {}
    for name, curve, pt in (("g1", dev.G1, g1pt), ("g2", dev.G2, g2pt)):
        ladder = jax.jit(lambda b, c=curve, p=pt: strict_x(
            c, c.msm_bits(p, b)))
        planes = jax.jit(lambda b, c=curve, p=pt: strict_x(
            c, digit_plane_msm(c, p, b)))
        t_l = time_honest(f"{name}_ladder", ladder, fresh_bits)
        t_p = time_honest(f"{name}_digitplane", planes, fresh_bits)
        results[name] = (t_l, t_p)
        print(f"{name}: digit-plane / ladder = {t_p / t_l:.2f}x", flush=True)

    # Self-contained ledger tail: this rung's own metric, never mixed
    # into the BLS headline trend.  Headline > 1 would mean the
    # digit-plane formulation finally beats the production ladder
    # (historically ~0.5x — the kept negative result).
    import json

    from consensus_overlord_tpu.obs import ledger
    g2_l, g2_p = results["g2"]
    print(json.dumps(ledger.build_record(
        "ladder_msm_digitplane_speedup_g2", round(g2_l / g2_p, 4), "x",
        context={"backend": jax.default_backend(), "batch": N,
                 "g1_ladder_ms": round(results["g1"][0], 2),
                 "g1_digitplane_ms": round(results["g1"][1], 2),
                 "g2_ladder_ms": round(g2_l, 2),
                 "g2_digitplane_ms": round(g2_p, 2)})))


if __name__ == "__main__":
    main()
