"""Raw-arithmetic microbench behind the r4 field-multiply rethink
(VERDICT r3 item 3): per-MAC cost int32 vs f32, and whether an
alternative formulation (f32 b=7 radix, MXU-shaped dot_general
Toeplitz contraction) can beat the int32 b=10 schoolbook convolution.

Measurement shape (the PJRT-relay honesty rules, BASELINE.md): inputs
stay ON DEVICE, each measured call chains K DEPENDENT applications
under one jit (no loop-invariant hoisting possible — every step
consumes the previous result), a fresh device salt decorrelates
iterations, and only a checksum scalar is downloaded.  A first timing
pass of this script uploaded fresh (8192, 512) arrays per call and
"measured" 345 ms per field-mul — that was the ~30 MB/s tunnel, not
the chip; kept as a warning.

Usage: python scripts/bench_field_radix.py [B] [K]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from consensus_overlord_tpu.compile_cache import enable

enable()
from consensus_overlord_tpu.ops.field import BLS12_381_FQ as FQ

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
K = int(sys.argv[2]) if len(sys.argv) > 2 else 64
ITERS = 6
rng = np.random.default_rng(7)
n = FQ.n


def timed(name, make_chain, *arrays, macs_per_step=None):
    """SLOPE timing: median call time at chain lengths K and 2K; the
    difference divided by K is the per-step cost with the fixed
    dispatch+readback round-trip of the PJRT tunnel (~120-200 ms
    regardless of work) subtracted out.  A flat-K version of this
    script measured every formulation at ~1.9 ms/step — that was the
    link floor, not the chip."""
    devs = [jnp.asarray(a) for a in arrays]

    def median_call(fn):
        ts = []
        for i in range(ITERS + 1):
            salt = jnp.int32(i) if devs[0].dtype == jnp.int32 \
                else jnp.float32(i)
            t0 = time.time()
            jax.device_get(fn(*devs, salt))
            ts.append(time.time() - t0)
        return sorted(ts[1:])[len(ts[1:]) // 2]

    t1 = median_call(jax.jit(make_chain(K)))
    t2 = median_call(jax.jit(make_chain(2 * K)))
    per_step = max((t2 - t1) / K, 1e-9)
    extra = ""
    if macs_per_step:
        extra = f"  ({macs_per_step / per_step / 1e9:6.1f} GMAC/s)"
    print(f"  {name:<40s} {per_step * 1e6:9.1f} us/step{extra}"
          f"   [K call {t1 * 1e3:.0f} ms, 2K {t2 * 1e3:.0f} ms]")
    return per_step


def main():
    print(f"backend={jax.default_backend()} B={B} K={K}")

    # -- 1. raw elementwise MAC cost (dependent chain) ------------------
    shape = (B, 512)
    yi = rng.integers(1, 1 << 11, shape, dtype=np.int32)

    def chain_i32(length):
        def fn(y, salt):
            def step(c, _):
                return (c * y + salt) & 0x3FFFFF, None
            c, _ = lax.scan(step, y + salt, None, length=length)
            return c.sum()
        return fn

    def chain_f32(length):
        def fn(y, salt):
            def step(c, _):
                c = c * y + salt
                # keep values bounded+exact: wrap at 2^22
                return c - jnp.floor(c * (1 / (1 << 22))) * (1 << 22), None
            c, _ = lax.scan(step, y + salt, None, length=length)
            return c.sum()
        return fn

    mac = B * 512
    print(f"-- elementwise mul+add, {shape}, dependent {K}-chain --")
    ti = timed("int32 mul+add+mask", chain_i32, yi, macs_per_step=mac)
    tf = timed("f32 mul+add+wrap", chain_f32, yi.astype(np.float32),
               macs_per_step=mac)
    print(f"  int32/f32 per-step ratio: {ti / tf:.2f}x "
          f"(f32 b=7 radix needs >2x to pay for its 2x limbs)")

    # -- 2. field-mul formulations (dependent chains) -------------------
    yl = rng.integers(0, FQ.loose_max + 1, (B, n), dtype=np.int32)
    fmac = B * n * n

    def field_chain(mul):
        def make(length):
            def fn(y, salt):
                def step(c, _):
                    return mul(c, y), None
                c, _ = lax.scan(
                    step, FQ.add(y, jnp.broadcast_to(salt, y.shape)),
                    None, length=length)
                return FQ.strict(c).sum()
            return fn
        return make

    chain_cur = field_chain(FQ.mul)

    print(f"-- field multiply chains, B={B} --")
    t_cur = timed("int32 b=10 n=39 shifted-add (current)", chain_cur, yl,
                  macs_per_step=fmac)

    # MXU-shaped: gather-built Toeplitz + batched dot_general, then the
    # SAME static reduce — bit-identical to FieldSpec.mul by the assert
    # below, so this is a drop-in formulation if it wins.
    idx = np.arange(2 * n - 1)[None, :] - np.arange(n)[:, None]
    mask = jnp.asarray(((idx >= 0) & (idx < n)).astype(np.int32))
    idxc = jnp.asarray(np.clip(idx, 0, n - 1))

    def mul_dotgen(x, y):
        T = y[:, idxc] * mask  # (B, n, 2n-1)
        conv = lax.dot_general(
            x[:, None, :], T, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32)[:, 0, :]
        return FQ._reduce(conv, FQ._conv_bounds())

    chain_dotgen = field_chain(mul_dotgen)

    t_dg = timed("int32 dot_general Toeplitz + reduce", chain_dotgen, yl,
                 macs_per_step=fmac)

    # staircase (the CPU-compile formulation) on TPU, for the record.
    def mul_stair(x, y):
        P = x[..., :, None] * y[..., None, :]
        P = jnp.pad(P, [(0, 0), (0, 0), (0, n)])
        flat = P.reshape(P.shape[:-2] + (2 * n * n,))[..., :2 * n * n - n]
        st = flat.reshape(flat.shape[:-1] + (n, 2 * n - 1))
        return FQ._reduce(st.sum(-2), FQ._conv_bounds())

    chain_stair = field_chain(mul_stair)

    t_st = timed("int32 staircase reshape + reduce", chain_stair, yl,
                 macs_per_step=fmac)

    # f32 b=7 n=55 conv + minimal carry wrap (NOT exact field math — a
    # cost floor for any real f32 reduce, which needs at least one
    # carry pass; decides whether the float radix is worth building).
    n7 = 55
    y7 = rng.integers(0, 1 << 9, (B, n7)).astype(np.float32)

    def chain_f32field(length):
        def fn(y, salt):
            def step(c, _):
                terms = [
                    jnp.pad(c[..., i:i + 1] * y, [(0, 0), (i, n7 - 1 - i)])
                    for i in range(n7)
                ]
                out = terms[0]
                for t in terms[1:]:
                    out = out + t
                hi = jnp.floor(out * (1.0 / (1 << 7)))
                lo = out - hi * (1 << 7)
                folded = lo[..., :n7] + hi[..., :n7] * 3.0  # stand-in fold
                return folded, None
            c, _ = lax.scan(step, y + salt, None, length=length)
            return c.sum()
        return fn

    t_f = timed("f32 b=7 n=55 conv + carry wrap", chain_f32field, y7,
                macs_per_step=B * n7 * n7)

    # Bit-identical check: dot_general formulation vs FieldSpec.mul.
    xs = jnp.asarray(rng.integers(0, FQ.loose_max + 1, (256, n),
                                  dtype=np.int32))
    ys = jnp.asarray(rng.integers(0, FQ.loose_max + 1, (256, n),
                                  dtype=np.int32))
    a = jax.device_get(jax.jit(FQ.mul)(xs, ys))
    b = jax.device_get(jax.jit(mul_dotgen)(xs, ys))
    assert np.array_equal(FQ.strict(jnp.asarray(a)),
                          FQ.strict(jnp.asarray(b))), "dot_general drifts"

    print("-- summary --")
    print(f"  dot_general/current {t_dg / t_cur:.2f}x, "
          f"staircase/current {t_st / t_cur:.2f}x, "
          f"f32(b=7 floor)/current {t_f / t_cur:.2f}x")

    # Self-contained ledger tail: the production formulation's useful
    # conv MAC rate — this rung's own metric, never mixed into the BLS
    # headline trend.
    import json

    from consensus_overlord_tpu.obs import ledger
    print(json.dumps(ledger.build_record(
        "ladder_field_mul_gmacs", round(fmac / t_cur / 1e9, 3), "gmac/s",
        context={"backend": jax.default_backend(), "batch": B, "chain": K,
                 "current_us_per_step": round(t_cur * 1e6, 2),
                 "dot_general_vs_current": round(t_dg / t_cur, 3),
                 "staircase_vs_current": round(t_st / t_cur, 3),
                 "f32_b7_floor_vs_current": round(t_f / t_cur, 3),
                 "i32_f32_mac_ratio": round(ti / tf, 3)})))


if __name__ == "__main__":
    main()
