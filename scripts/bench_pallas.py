"""Measure the Pallas field-mul kernel vs the XLA FieldSpec path on the
current backend (meaningful on real TPU; CPU runs interpret mode).

Usage: python scripts/bench_pallas.py [batch]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp
import numpy as np

from consensus_overlord_tpu.compile_cache import enable

enable()

from consensus_overlord_tpu.ops.field import BLS12_381_FQ as FQ
from consensus_overlord_tpu.ops.pallas_field import mul_transposed

B = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
REPS = 64  # chained muls per timed call, so dispatch cost amortizes


def main():
    print(f"backend={jax.default_backend()} B={B} reps={REPS}")
    rng = np.random.default_rng(0)
    x = jnp.asarray(FQ.from_ints(
        [int.from_bytes(rng.bytes(47), "big") for _ in range(B)]))
    y = jnp.asarray(FQ.from_ints(
        [int.from_bytes(rng.bytes(47), "big") for _ in range(B)]))

    @jax.jit
    def xla_chain(x, y):
        for _ in range(REPS):
            x = FQ.mul(x, y)
        return x

    mul = mul_transposed(FQ)

    @jax.jit
    def pallas_chain(xT, yT):
        for _ in range(REPS):
            xT = mul(xT, yT)
        return xT

    def timeit(label, fn, *args):
        jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _ in range(4):
            jax.block_until_ready(fn(*args))
        dt = (time.perf_counter() - t0) / 4
        per = dt / REPS / B * 1e9
        print(f"{label:14s} {dt * 1e3:8.2f} ms/chain  {per:8.1f} ns/mul/lane")
        return dt

    t_x = timeit("xla_mul", xla_chain, x, y)
    xT = jnp.moveaxis(x, 0, 1)
    yT = jnp.moveaxis(y, 0, 1)
    t_p = timeit("pallas_mul", pallas_chain, xT, yT)
    print(f"pallas/xla speed ratio: {t_x / t_p:.2f}x")

    got = FQ.to_ints(jnp.moveaxis(pallas_chain(xT, yT), 0, 1))
    want = FQ.to_ints(xla_chain(x, y))
    assert got == want, "pallas chain diverged from XLA chain"
    print("correctness: chained results identical")

    # Self-contained ledger tail (obs/ledger.py): this rung's own
    # metric, never mixed into the BLS headline trend.
    import json

    from consensus_overlord_tpu.obs import ledger
    print(json.dumps(ledger.build_record(
        "ladder_pallas_field_mul_ratio_vs_xla",
        round(t_x / t_p, 4), "x",
        context={"backend": jax.default_backend(), "batch": B,
                 "reps": REPS,
                 "xla_ns_per_mul_lane": round(t_x / REPS / B * 1e9, 2),
                 "pallas_ns_per_mul_lane": round(t_p / REPS / B * 1e9, 2)})))


if __name__ == "__main__":
    main()
