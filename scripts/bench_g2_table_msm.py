"""On-chip A/B: G2 MSM via per-pubkey PRECOMPUTED window tables vs the
windowed double-and-add ladder (r4, VERDICT item 3 follow-on).

The r3/r4 ledger killed every arithmetic reformulation of the field
multiply (Pippenger 2.1x slower, Pallas ~1.0x, dot_general-Toeplitz
2.18x, staircase 4.58x, f32-radix floor 1.79x; the current mul runs at
~47% of the chip's practical int32 elementwise ceiling).  The remaining
structural lever: the verify relation's G2 MSM Σ r_i·P_i runs over
pubkeys that are CACHED on device between reconfigures, so the
16-window × 16-digit multiples d·16^j·P_i can be precomputed ONCE per
reconfigure.  Per lane the MSM then costs 16 table gathers + 15 adds —
the 64 accumulator doublings (the ladder's dominant term: 64 of 80
point ops) vanish from the per-round path.

Memory: 256 points/key × 936 B (projective 2×39-limb int32 ×3 coords)
≈ 240 KB/key → 2.0 GB at 8192 cached keys (v5e HBM 16 GB).

This script measures both formulations at B lanes with fresh 64-bit
scalars per iteration (slope timing over a dependent chain is
impossible here — an MSM is one reduction — so it uses distinct-input
dispatch pipelining like bench.py) and asserts bit-identical strict
affine outputs.

Usage: python scripts/bench_g2_table_msm.py [B] [ITERS]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from consensus_overlord_tpu.compile_cache import enable

enable()
from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.ops import bls12381_groups as dev
from consensus_overlord_tpu.ops.curve import Point

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
ITERS = int(sys.argv[2]) if len(sys.argv) > 2 else 6
WINDOWS = 16  # 64-bit RLC weights, w=4
DIGITS = 16


# The formulations under test are PRODUCTION code now (r12 promoted the
# winner behind tpu_provider's g2_table_msm knob): table build + gather
# MSM live in ops/curve.py; this script stays the reproducible A/B.

def build_tables(pk: Point) -> Point:
    return dev.G2.msm_table_build(pk, windows=WINDOWS, digits=DIGITS)


def msm_tables(tab: Point, rows, bits) -> Point:
    return dev.G2.msm_from_tables(tab, rows, bits)


def main():
    print(f"backend={jax.default_backend()} B={B}")
    rng = np.random.default_rng(11)

    # Distinct pubkeys, one cache row per lane (worst case for tables).
    sks = [1000 + 7 * i for i in range(B)]
    pks_aff = [oracle.g2_decompress(oracle.sk_to_pk(sk)) for sk in sks]
    pk = dev.g2_from_oracle(pks_aff)
    rows = jnp.arange(B, dtype=jnp.int64)

    t0 = time.time()
    tab = jax.block_until_ready(jax.jit(build_tables)(pk))
    t_build = time.time() - t0
    gb = sum(a.nbytes for a in (tab.x, tab.y, tab.z)) / 1e9
    print(f"  table build (one-time, incl. compile): {t_build:.1f} s, "
          f"{gb:.2f} GB on device")

    ladder = jax.jit(lambda p, bits: dev.G2.msm_bits(p, bits))
    tmsm = jax.jit(lambda tab_, rows_, bits: msm_tables(tab_, rows_, bits))

    @jax.jit
    def aff(p):
        # STRICT affine coords: to_affine alone returns loose limbs,
        # which differ between projective representatives of the same
        # point — comparing those reports false drift.
        ax, ay, ainf = dev.G2.to_affine(p)
        return dev.FQ.strict(ax), dev.FQ.strict(ay), ainf

    def run(fn, *args):
        return jax.device_get(aff(fn(*args)))

    def bench(name, dispatch):
        # fresh scalars per iteration (the relay dedupes identical work)
        ts = []
        out = None
        for i in range(ITERS + 1):
            w = rng.integers(0, 2, (B, 64), dtype=np.int64).astype(np.int32)
            w[:, 0] = 1
            bits = jnp.asarray(w)
            jax.block_until_ready(bits)
            t0 = time.time()
            out = dispatch(bits)
            ts.append(time.time() - t0)
        med = sorted(ts[1:])[len(ts[1:]) // 2]
        print(f"  {name:<34s} {med * 1e3:8.1f} ms/MSM")
        return med, out

    t_lad, _ = bench("windowed ladder (current)",
                     lambda bits: run(ladder, pk, bits))
    t_tab, _ = bench("precomputed tables (gather+add)",
                     lambda bits: run(tmsm, tab, rows, bits))

    # Bit-identical outputs on one fixed scalar set.
    w = rng.integers(0, 2, (B, 64), dtype=np.int64).astype(np.int32)
    w[:, 0] = 1
    bits = jnp.asarray(w)
    a = run(ladder, pk, bits)
    b = run(tmsm, tab, rows, bits)
    for xa, xb in zip(a, b):
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), "MSM drift"

    print(f"-- summary: tables/ladder {t_tab / t_lad:.2f}x "
          f"({'WIN' if t_tab < t_lad else 'LOSS'}) --")

    # Self-contained ledger tail: this rung's own metric, never mixed
    # into the BLS headline trend.  Headline > 1 = tables beat the
    # ladder (the condition for flipping g2_table_msm on by default).
    import json

    from consensus_overlord_tpu.obs import ledger
    print(json.dumps(ledger.build_record(
        "ladder_g2_table_msm_speedup", round(t_lad / t_tab, 4), "x",
        context={"backend": jax.default_backend(), "batch": B,
                 "iters": ITERS,
                 "ladder_ms_per_msm": round(t_lad * 1e3, 2),
                 "tables_ms_per_msm": round(t_tab * 1e3, 2),
                 "table_build_s": round(t_build, 2),
                 "table_gb_on_device": round(gb, 3)})))


if __name__ == "__main__":
    main()
