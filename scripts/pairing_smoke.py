"""CI pairing smoke: device multi-pairing verdict identity vs the host
oracle at N=4 (sub-minute on the CPU lane with a warm compile cache).

Checks, per randomized (sig, pk, msg) set (half of them invalid):
  * the device staged verdict kernels (ops/pairing.py — batched Miller
    loop + ONE shared final exponentiation) agree with
    crypto/bls12381.py multi_pairing_is_one bit-for-bit;
  * the verdicts match the a-priori expectation (valid sets True,
    tampered sets False).

Exit 0 on full agreement, 1 with a per-set report otherwise.

Usage: python scripts/pairing_smoke.py [N]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from consensus_overlord_tpu.compile_cache import enable

enable()

import jax.numpy as jnp
import numpy as np

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.ops import pairing as pr

N = int(sys.argv[1]) if len(sys.argv) > 1 else 4


def main() -> int:
    neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
    failures = 0
    for i in range(N):
        sk = 0xC0FFEE + 31 * i
        h = sm3_hash(b"pairing-smoke-%d" % i)
        sig = oracle.g1_decompress(oracle.sign(sk, h))
        pk = oracle.g2_decompress(oracle.sk_to_pk(sk))
        if i % 2 == 1:
            sig = oracle.g1_mul(sig, 7)  # valid point, forged signature
        h_pt = oracle.hash_to_g1(h, b"")
        want = i % 2 == 0

        px, py, pinf = pr.g1_affine_from_oracle([sig, h_pt])
        qx, qy, qinf = pr.g2_affine_from_oracle([neg_g2, pk])
        got = bool(pr.multi_pairing_is_one_staged(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(pinf),
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(qinf),
            jnp.asarray(np.ones(2, bool))))
        host = oracle.multi_pairing_is_one([(sig, neg_g2), (h_pt, pk)])
        ok = got == host == want
        print(f"set {i}: device={got} host={host} expected={want}"
              f" {'OK' if ok else 'MISMATCH'}", flush=True)
        failures += 0 if ok else 1
    if failures:
        print(f"FAIL: {failures}/{N} sets disagree")
        return 1
    print(f"ok: {N}/{N} device verdicts identical to the host oracle")
    return 0


if __name__ == "__main__":
    sys.exit(main())
