"""CI pairing smoke: device multi-pairing verdict identity vs the host
oracle at N=4 (sub-minute on the CPU lane with a warm compile cache).

Checks, per randomized (sig, pk, msg) set (half of them invalid):
  * the device staged verdict kernels (ops/pairing.py — batched Miller
    loop + ONE shared final exponentiation) agree with
    crypto/bls12381.py multi_pairing_is_one bit-for-bit;
  * the verdicts match the a-priori expectation (valid sets True,
    tampered sets False).

--mesh D runs the SHARDED staged pair instead (parallel/sharded.py
sharded_multi_pairing_is_one) over a D-lane virtual CPU mesh
(--xla_force_host_platform_device_count, set before jax initializes):
pair lanes shard across the mesh, each device Miller-loops its shard,
the D Fq12 partials all-gather, and every device finishes the identical
product + final exponentiation — the verdict must still be bit-identical
to the host oracle.  Pairs pad up to the mesh size with masked lanes.

Exit 0 on full agreement, 1 with a per-set report otherwise.

Usage: python scripts/pairing_smoke.py [N] [--mesh D]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

_args = [a for a in sys.argv[1:] if not a.startswith("-")]
N = int(_args[0]) if _args else 4
MESH = 0
if "--mesh" in sys.argv:
    MESH = int(sys.argv[sys.argv.index("--mesh") + 1])
    # Virtual devices: the flag must land before the CPU backend
    # initializes — before ANY jax import (compile_cache pulls jax in).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={MESH}"
        ).strip()

from consensus_overlord_tpu.compile_cache import enable

enable()

import jax
import jax.numpy as jnp
import numpy as np

if MESH:
    jax.config.update("jax_platforms", "cpu")

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.ops import pairing as pr


def _verdict_fn():
    """The device verdict under test: the single-chip staged pair, or
    the sharded mesh pair under --mesh (same verdict contract)."""
    if not MESH:
        return pr.multi_pairing_is_one_staged
    from consensus_overlord_tpu.parallel import (
        make_mesh,
        sharded_multi_pairing_is_one,
    )

    mesh = make_mesh(MESH)
    assert mesh.devices.size == MESH, \
        f"virtual mesh has {mesh.devices.size} devices, wanted {MESH}"
    return sharded_multi_pairing_is_one(mesh)


def main() -> int:
    neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
    verdict = _verdict_fn()
    lanes = MESH or 1
    failures = 0
    for i in range(N):
        sk = 0xC0FFEE + 31 * i
        h = sm3_hash(b"pairing-smoke-%d" % i)
        sig = oracle.g1_decompress(oracle.sign(sk, h))
        pk = oracle.g2_decompress(oracle.sk_to_pk(sk))
        if i % 2 == 1:
            sig = oracle.g1_mul(sig, 7)  # valid point, forged signature
        h_pt = oracle.hash_to_g1(h, b"")
        want = i % 2 == 0

        # Pad the 2-pair set up to a lanes multiple with masked lanes
        # (the provider's ladder does the same on the mesh path).
        size = -(-2 // lanes) * lanes
        pad = [None] * (size - 2)
        px, py, pinf = pr.g1_affine_from_oracle([sig, h_pt] + pad)
        qx, qy, qinf = pr.g2_affine_from_oracle([neg_g2, pk] + pad)
        mask = np.arange(size) < 2
        got = bool(verdict(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(pinf),
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(qinf),
            jnp.asarray(mask)))
        host = oracle.multi_pairing_is_one([(sig, neg_g2), (h_pt, pk)])
        ok = got == host == want
        print(f"set {i}: device={got} host={host} expected={want}"
              f" {'OK' if ok else 'MISMATCH'}", flush=True)
        failures += 0 if ok else 1
    kind = f"mesh({MESH})" if MESH else "device"
    if failures:
        print(f"FAIL: {failures}/{N} sets disagree")
        return 1
    print(f"ok: {N}/{N} {kind} verdicts identical to the host oracle")
    return 0


if __name__ == "__main__":
    sys.exit(main())
