"""CI pairing smoke: device multi-pairing verdict identity vs the host
oracle at N=4 (sub-minute on the CPU lane with a warm compile cache).

Checks, per randomized (sig, pk, msg) set (half of them invalid):
  * the device staged verdict kernels (ops/pairing.py — batched Miller
    loop + ONE shared final exponentiation) agree with
    crypto/bls12381.py multi_pairing_is_one bit-for-bit;
  * the verdicts match the a-priori expectation (valid sets True,
    tampered sets False).

--mesh D runs the SHARDED staged pair instead (parallel/sharded.py
sharded_multi_pairing_is_one) over a D-lane virtual CPU mesh
(--xla_force_host_platform_device_count, set before jax initializes):
pair lanes shard across the mesh, each device Miller-loops its shard,
the D Fq12 partials all-gather, and every device finishes the identical
product + final exponentiation — the verdict must still be bit-identical
to the host oracle.  Pairs pad up to the mesh size with masked lanes.

--inject-loss LANE (mesh mode only) additionally exercises one
self-healing ladder step end-to-end through the production provider
(crypto/tpu_provider.py + parallel/supervisor.py): warm a full-mesh
verify, lose lane LANE mid-run, and require that the supervisor
quarantines exactly that lane, rebuilds a (D-1)-lane sub-mesh, and the
sub-mesh verdicts stay bit-identical to the host oracle.

Exit 0 on full agreement, 1 with a per-set report otherwise.

Usage: python scripts/pairing_smoke.py [N] [--mesh D] [--inject-loss LANE]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

_flag_vals = set()
for _f in ("--mesh", "--inject-loss"):
    if _f in sys.argv:
        _flag_vals.add(sys.argv.index(_f) + 1)
_args = [a for i, a in enumerate(sys.argv[1:], start=1)
         if not a.startswith("-") and i not in _flag_vals]
N = int(_args[0]) if _args else 4
INJECT_LOSS = -1
if "--inject-loss" in sys.argv:
    INJECT_LOSS = int(sys.argv[sys.argv.index("--inject-loss") + 1])
MESH = 0
if "--mesh" in sys.argv:
    MESH = int(sys.argv[sys.argv.index("--mesh") + 1])
    # Virtual devices: the flag must land before the CPU backend
    # initializes — before ANY jax import (compile_cache pulls jax in).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={MESH}"
        ).strip()

from consensus_overlord_tpu.compile_cache import enable

enable()

import jax
import jax.numpy as jnp
import numpy as np

if MESH:
    jax.config.update("jax_platforms", "cpu")

from consensus_overlord_tpu.core.sm3 import sm3_hash
from consensus_overlord_tpu.crypto import bls12381 as oracle
from consensus_overlord_tpu.ops import pairing as pr


def _verdict_fn():
    """The device verdict under test: the single-chip staged pair, or
    the sharded mesh pair under --mesh (same verdict contract)."""
    if not MESH:
        return pr.multi_pairing_is_one_staged
    from consensus_overlord_tpu.parallel import (
        make_mesh,
        sharded_multi_pairing_is_one,
    )

    mesh = make_mesh(MESH)
    assert mesh.devices.size == MESH, \
        f"virtual mesh has {mesh.devices.size} devices, wanted {MESH}"
    return sharded_multi_pairing_is_one(mesh)


def _ladder_smoke() -> int:
    """--inject-loss LANE: one self-healing ladder step, end to end.

    full_mesh verify (warm) -> inject_device_loss(LANE) -> the loss
    surfaces as a DeviceLossError, the verdicts come from the exact host
    fallback, the supervisor quarantines the named lane and rebuilds a
    (D-1)-lane sub-mesh -> the sub-mesh dispatch runs clean while the
    lane is still lost, verdicts bit-identical to the host oracle.
    """
    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto
    from consensus_overlord_tpu.parallel import make_mesh
    from consensus_overlord_tpu.parallel.supervisor import MeshSupervisor

    provider = TpuBlsCrypto(0xD1CE, device_threshold=1,
                            mesh=make_mesh(MESH))
    # One failure steps down; the huge probe budget + dwell keep the
    # ladder parked on sub_mesh for the rest of the smoke.
    sup = MeshSupervisor(provider, step_threshold=1,
                         probe_successes=10_000, probe_cooldown_s=3600.0)
    provider.attach_supervisor(sup)

    batch = 2 * MESH
    h = sm3_hash(b"ladder-smoke-block")
    sks = [9000 + 17 * i for i in range(batch)]
    sigs = [oracle.sign(sk, h) for sk in sks]
    pks = [oracle.sk_to_pk(sk) for sk in sks]
    provider.update_pubkeys(pks)
    expect = [i != 3 for i in range(batch)]  # one forged lane, like main()
    sigs[3] = oracle.sign(sks[3], sm3_hash(b"other message"))

    got = provider.verify_batch(sigs, [h] * batch, pks)
    if got != expect or sup.rung != "full_mesh":
        print(f"FAIL: full-mesh verdicts {got} (rung={sup.rung})")
        return 1
    print(f"full_mesh: {batch}-sig verdicts identical to the host oracle",
          flush=True)

    lane = provider.mesh_device_names()[INJECT_LOSS]
    provider.inject_device_loss(lane, seconds=3600.0)
    got = provider.verify_batch(sigs, [h] * batch, pks)
    if got != expect:
        print(f"FAIL: host-fallback verdicts wrong under loss: {got}")
        return 1
    if sup.rung != "sub_mesh" or sup.quarantined_devices() != [lane]:
        print(f"FAIL: wanted sub_mesh quarantining [{lane}], got "
              f"rung={sup.rung} quarantined={sup.quarantined_devices()}")
        return 1
    print(f"lane {lane} lost: exact host fallback, supervisor stepped "
          f"full_mesh -> sub_mesh ({MESH - 1} lanes)", flush=True)

    fallbacks0 = provider.breaker.total_fallbacks
    got = provider.verify_batch(sigs, [h] * batch, pks)
    if got != expect:
        print(f"FAIL: sub-mesh verdicts wrong: {got}")
        return 1
    if provider.breaker.total_fallbacks != fallbacks0:
        print("FAIL: sub-mesh pass fell back to the host "
              "(the rebuilt kernels should dispatch clean)")
        return 1
    print(f"ok: sub-mesh verdicts identical to the host oracle with "
          f"lane {lane} still lost")
    return 0


def main() -> int:
    neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
    verdict = _verdict_fn()
    lanes = MESH or 1
    failures = 0
    for i in range(N):
        sk = 0xC0FFEE + 31 * i
        h = sm3_hash(b"pairing-smoke-%d" % i)
        sig = oracle.g1_decompress(oracle.sign(sk, h))
        pk = oracle.g2_decompress(oracle.sk_to_pk(sk))
        if i % 2 == 1:
            sig = oracle.g1_mul(sig, 7)  # valid point, forged signature
        h_pt = oracle.hash_to_g1(h, b"")
        want = i % 2 == 0

        # Pad the 2-pair set up to a lanes multiple with masked lanes
        # (the provider's ladder does the same on the mesh path).
        size = -(-2 // lanes) * lanes
        pad = [None] * (size - 2)
        px, py, pinf = pr.g1_affine_from_oracle([sig, h_pt] + pad)
        qx, qy, qinf = pr.g2_affine_from_oracle([neg_g2, pk] + pad)
        mask = np.arange(size) < 2
        got = bool(verdict(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(pinf),
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(qinf),
            jnp.asarray(mask)))
        host = oracle.multi_pairing_is_one([(sig, neg_g2), (h_pt, pk)])
        ok = got == host == want
        print(f"set {i}: device={got} host={host} expected={want}"
              f" {'OK' if ok else 'MISMATCH'}", flush=True)
        failures += 0 if ok else 1
    kind = f"mesh({MESH})" if MESH else "device"
    if failures:
        print(f"FAIL: {failures}/{N} sets disagree")
        return 1
    print(f"ok: {N}/{N} {kind} verdicts identical to the host oracle")
    if INJECT_LOSS >= 0:
        if not MESH:
            print("FAIL: --inject-loss needs --mesh D")
            return 1
        return _ladder_smoke()
    return 0


if __name__ == "__main__":
    sys.exit(main())
