"""Derive the 11-isogeny E' -> E for BLS12-381 G1 SSWU hashing (RFC 9380
§6.6.2/§8.8.1) from first principles, and print its rational-map
coefficients as Python literals for crypto/hash_to_curve.py.

Why derive instead of transcribe: the map has 4 polynomials totalling ~50
96-hex-char coefficients; a transcription error would be silent until a
cross-implementation interop failure.  Here the coefficients are COMPUTED
(division polynomial -> rational kernel -> Vélu's formulas) and verified
structurally (mapped points land on E: y² = x³ + 4; the map is a group
homomorphism), then pinned by RFC known-answer vectors in
tests/test_hash_to_curve.py.

Method:
  1. E': y² = x³ + A'x + B' is the isogenous curve of the ciphersuite
     (A', B' from RFC 9380 §8.8.1).  Compute its 11-division polynomial
     ψ₁₁(x) (degree 60) over Fp.
  2. gcd(x^p − x, ψ₁₁) isolates the x-coordinates of rational 11-torsion;
     split to roots (Cantor–Zassenhaus), group the roots into order-11
     subgroups by generating multiples of a lifted point over Fp².
  3. Vélu's formulas over the kernel give X(x) = X_num/h², Y(x,y) =
     y·Y_num/h³ and the image curve — which must equal E (b = 4, a = 0)
     for the right kernel/normalization.
  4. Print the coefficient lists low-degree-first.

Pure Python, stdlib only; runs in ~1 minute.  Output is baked into
crypto/hash_to_curve.py (regenerate with: python scripts/derive_g1_isogeny.py).
"""

import random
import sys

P = int(
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f624"
    "1eabfffeb153ffffb9feffffffffaaab", 16)

# RFC 9380 §8.8.1: the isogenous curve E' for BLS12381G1_XMD:SHA-256_SSWU_RO_
A_PRIME = int(
    "144698a3b8e9433d693a02c96d4982b0ea985383ee66a8d8e8981aefd881ac98"
    "936f8da0e0f97f5cf428082d584c1d", 16)
B_PRIME = int(
    "12e2908d11688030018b12e8753eee3b2016c1f0f24f4070a0b9c14fcef35ef5"
    "5a23215a316ceaa5d1cc48e98e172be0", 16)

# Target curve E: y² = x³ + 4
A_E, B_E = 0, 4


# -- Fp[x] dense polynomial arithmetic (coefficients low-degree-first) -------

def pnorm(f):
    while f and f[-1] == 0:
        f.pop()
    return f


def padd(f, g):
    n = max(len(f), len(g))
    return pnorm([((f[i] if i < len(f) else 0) +
                   (g[i] if i < len(g) else 0)) % P for i in range(n)])


def psub(f, g):
    n = max(len(f), len(g))
    return pnorm([((f[i] if i < len(f) else 0) -
                   (g[i] if i < len(g) else 0)) % P for i in range(n)])


def pmul(f, g):
    if not f or not g:
        return []
    out = [0] * (len(f) + len(g) - 1)
    for i, a in enumerate(f):
        if a:
            for j, b in enumerate(g):
                out[i + j] = (out[i + j] + a * b) % P
    return pnorm(out)


def pscale(f, c):
    return pnorm([a * c % P for a in f])


def pdivmod(f, g):
    f = list(f)
    q = [0] * max(1, len(f) - len(g) + 1)
    inv_lead = pow(g[-1], P - 2, P)
    while len(f) >= len(g):
        c = f[-1] * inv_lead % P
        d = len(f) - len(g)
        q[d] = c
        for i, b in enumerate(g):
            f[d + i] = (f[d + i] - c * b) % P
        pnorm(f)
        if not f:
            break
    return pnorm(q), f


def pmod(f, g):
    return pdivmod(f, g)[1]


def pgcd(f, g):
    while g:
        f, g = g, pmod(f, g)
    return pscale(f, pow(f[-1], P - 2, P)) if f else f


def ppowmod(f, e, m):
    r = [1]
    f = pmod(f, m)
    while e:
        if e & 1:
            r = pmod(pmul(r, f), m)
        f = pmod(pmul(f, f), m)
        e >>= 1
    return r


# -- division polynomials of E' (y² = x³ + ax + b) ---------------------------

def division_polys(a, b, upto):
    """ψ_n as univariate polys: odd n directly; even n as ψ_n / (2y)
    with y² = f(x) substituted (the standard trick).  Returns dict n→poly
    plus a parallel dict marking whether the poly carries a factor that
    must be multiplied by 2y (even index)."""
    f = [b % P, a % P, 0, 1]  # x³ + ax + b
    # Representation: odd-index ψ_n stored directly; even-index stored as
    # ψ̃_n = ψ_n / (2y).  With F = (2y)² = 4f the recurrences close over
    # stored values:
    #   n = 2m+1, m even : ψ_n = F²·ψ̃_{m+2}ψ̃_m³ − ψ_{m−1}ψ_{m+1}³
    #   n = 2m+1, m odd  : ψ_n = ψ_{m+2}ψ_m³ − F²·ψ̃_{m−1}ψ̃_{m+1}³
    #   n = 2m           : ψ̃_n = s_m·(s_{m+2}·s_{m−1}² − s_{m−2}·s_{m+1}²)
    #                      (s = stored value; the (2y) factors cancel
    #                      identically for both parities of m)
    psi = {0: [], 1: [1], 2: [1]}
    # ψ3 = 3x⁴ + 6ax² + 12bx − a²
    psi[3] = pnorm([(-a * a) % P, (12 * b) % P, (6 * a) % P, 0, 3])
    # ψ̃4 = 2(x⁶ + 5ax⁴ + 20bx³ − 5a²x² − 4abx − 8b² − a³)
    psi[4] = pscale(pnorm([(-8 * b * b - a ** 3) % P, (-4 * a * b) % P,
                           (-5 * a * a) % P, (20 * b) % P, (5 * a) % P,
                           0, 1]), 2)
    F = pscale(f, 4)
    F2 = pmul(F, F)
    for n in range(5, upto + 1):
        m = n // 2
        if n % 2 == 1:
            t1 = pmul(psi[m + 2], pmul(psi[m], pmul(psi[m], psi[m])))
            t2 = pmul(psi[m - 1], pmul(psi[m + 1],
                                       pmul(psi[m + 1], psi[m + 1])))
            if m % 2 == 0:
                psi[n] = psub(pmul(t1, F2), t2)
            else:
                psi[n] = psub(t1, pmul(t2, F2))
        else:
            t1 = pmul(psi[m + 2], pmul(psi[m - 1], psi[m - 1]))
            t2 = pmul(psi[m - 2], pmul(psi[m + 1], psi[m + 1]))
            psi[n] = pmul(psi[m], psub(t1, t2))
    return psi


# -- root finding ------------------------------------------------------------

def roots_of(fpoly):
    """All Fp roots of fpoly (Cantor–Zassenhaus on the linear-factor part)."""
    xp = ppowmod([0, 1], P, fpoly)
    lin = pgcd(psub(xp, [0, 1]), fpoly)
    out = []

    def split(g):
        if len(g) == 2:  # x + c
            out.append((-g[0]) * pow(g[1], P - 2, P) % P)
            return
        if len(g) <= 1:
            return
        while True:
            delta = random.randrange(P)
            t = ppowmod([delta, 1], (P - 1) // 2, g)
            h = pgcd(psub(t, [1]), g)
            if 0 < len(h) - 1 < len(g) - 1:
                split(h)
                split(pdivmod(g, h)[0])
                return

    split(lin)
    return sorted(out)


# -- Fp² and curve arithmetic over it ---------------------------------------

class F2:
    """Fp[u]/(u²+1) — enough to lift kernel points whose y lives there."""

    __slots__ = ("a", "b")

    def __init__(self, a, b=0):
        self.a, self.b = a % P, b % P

    def __add__(s, o):
        return F2(s.a + o.a, s.b + o.b)

    def __sub__(s, o):
        return F2(s.a - o.a, s.b - o.b)

    def __mul__(s, o):
        return F2(s.a * o.a - s.b * o.b, s.a * o.b + s.b * o.a)

    def __eq__(s, o):
        return s.a == o.a and s.b == o.b

    def inv(s):
        d = pow((s.a * s.a + s.b * s.b) % P, P - 2, P)
        return F2(s.a * d, -s.b * d)

    def sqrt(s):
        """Square root in Fp² (complex method); None if non-square."""
        if s.b == 0:
            r = pow(s.a, (P + 1) // 4, P)
            if r * r % P == s.a:
                return F2(r)
            # sqrt(a) = sqrt(-a)·u
            r = pow((-s.a) % P, (P + 1) // 4, P)
            if r * r % P == (-s.a) % P:
                return F2(0, r)
            return None
        norm = (s.a * s.a + s.b * s.b) % P
        n = pow(norm, (P + 1) // 4, P)
        if n * n % P != norm:
            return None
        for sgn in (1, -1):
            alpha = (s.a + sgn * n) % P * pow(2, P - 2, P) % P
            t = pow(alpha, (P + 1) // 4, P)
            if t * t % P == alpha:
                if t == 0:
                    continue
                c1 = s.b * pow(2 * t % P, P - 2, P) % P
                cand = F2(t, c1)
                if cand * cand == s:
                    return cand
        return None


def ec_add2(p1, p2, a):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) == F2(0):
            return None
        lam = (F2(3) * x1 * x1 + F2(a)) * (F2(2) * y1).inv()
    else:
        lam = (y2 - y1) * (x2 - x1).inv()
    x3 = lam * lam - x1 - x2
    return (x3, lam * (x1 - x3) - y1)


def main():
    random.seed(0xC0FFEE)
    sys.setrecursionlimit(10000)
    psi = division_polys(A_PRIME, B_PRIME, 11)
    psi11 = psi[11]
    print(f"deg psi11 = {len(psi11) - 1}", file=sys.stderr)
    xs = roots_of(psi11)
    print(f"rational 11-torsion x-coords: {len(xs)}", file=sys.stderr)

    # Group roots into order-11 subgroups: lift one root to a point over
    # Fp², generate its multiples, collect the 5 distinct x-coords.
    f_of = lambda x: (pow(x, 3, P) + A_PRIME * x + B_PRIME) % P
    remaining = set(xs)
    kernels = []
    while remaining:
        x0 = next(iter(remaining))
        y0 = F2(f_of(x0)).sqrt()
        assert y0 is not None, "y lift failed"
        q = (F2(x0), y0)
        pt = q
        kx = set()
        for _ in range(5):
            assert pt is not None
            assert pt[0].b == 0, "kernel x-coord not rational?"
            kx.add(pt[0].a)
            pt = ec_add2(pt, q, A_PRIME)
        # 6q..10q mirror 5q..1q; the 11th multiple must be O — this is
        # the division-polynomial correctness check.
        for _ in range(5):
            pt = ec_add2(pt, q, A_PRIME)
        assert pt is None, "lifted kernel point does not have order 11"
        kernels.append(sorted(kx))
        remaining -= kx
    print(f"{len(kernels)} rational order-11 kernel(s)", file=sys.stderr)

    for ker in kernels:
        # Vélu over the half-kernel S = the 5 x-coords.
        h = [1]
        for xq in ker:
            h = pmul(h, [(-xq) % P, 1])
        v = w = 0
        per_q = []
        for xq in ker:
            gq = (3 * xq * xq + A_PRIME) % P
            uq = 4 * f_of(xq) % P
            vq = 2 * gq % P
            v = (v + vq) % P
            w = (w + uq + xq * vq) % P
            per_q.append((xq, vq, uq))
        a2 = (A_PRIME - 5 * v) % P
        b2 = (B_PRIME - 7 * w) % P
        print(f"kernel -> image curve a={hex(a2)} b={hex(b2)}",
              file=sys.stderr)
        if a2 == A_E:
            break
    else:
        raise SystemExit("no kernel gives an a=0 image — check A'/B'")

    # X(x) = [x·h² + Σ (vq·(h/(x−xq))·h + uq·(h/(x−xq))²)] / h²
    h2 = pmul(h, h)
    h3 = pmul(h2, h)
    x_num = pmul([0, 1], h2)
    y_num = list(h3)
    for xq, vq, uq in per_q:
        hq, rem = pdivmod(h, [(-xq) % P, 1])
        assert not rem
        hq2 = pmul(hq, hq)
        hq3 = pmul(hq2, hq)
        x_num = padd(x_num, pscale(pmul(hq, h), vq))
        x_num = padd(x_num, pscale(hq2, uq))
        y_num = psub(y_num, pscale(hq3, 2 * uq % P))
        y_num = psub(y_num, pscale(pmul(hq2, h), vq))
    x_den, y_den = h2, h3

    # Compose with the isomorphism (x, y) → (u²x, u³y) taking the Vélu
    # image y² = x³ + b2 onto E: y² = x³ + 4 (u⁶ = 4/b2).  Six choices of
    # u (Aut(E) has order 6 at j = 0); the RFC's normalization is pinned
    # by the known low coefficient of its x_num (k_(1,0), RFC 9380 E.2).
    K10_RFC = int(
        "11a05f2b1e833340b809101dd99815856b303e88a2d7005ff2627b56cdb4e2c8"
        "5610c2d5f2e62d6eaeac1662734649b7", 16)
    c = 4 * pow(b2, P - 2, P) % P
    # All six u with u⁶ = c, via the same root finder used on ψ₁₁
    # (p ≡ 1 mod 9, so no closed-form cube-root exponent exists).
    candidates = roots_of([(-c) % P, 0, 0, 0, 0, 0, 1])
    assert candidates, "4/b2 has no sixth root — unexpected twist class"
    for u in candidates:
        assert pow(u, 6, P) == c
    # NOTE: k_(1,0) pins u only up to sign (±u share u²); the y-map sign
    # is pinned downstream by the RFC known-answer vectors
    # (tests/test_hash_to_curve.py) — if a regeneration flips them,
    # negate ISO_Y_NUM mod p.
    chosen = None
    for u in candidates:
        xn = pscale(x_num, u * u % P)
        if xn[0] == K10_RFC:
            chosen = u
            break
    if chosen is None:
        print("WARNING: no u matches the RFC k_(1,0) constant; "
              "candidates' k10 values:", file=sys.stderr)
        for u in candidates:
            print(f"  u={hex(u)} k10={hex(pscale(x_num, u*u%P)[0])}",
                  file=sys.stderr)
        chosen = candidates[0]
    u = chosen
    x_num = pscale(x_num, u * u % P)
    y_num = pscale(y_num, pow(u, 3, P))

    # -- structural verification over random points of E'(Fp) -------------
    def eval_poly(f, x):
        acc = 0
        for c in reversed(f):
            acc = (acc * x + c) % P
        return acc

    def iso(pt):
        if pt is None:
            return None
        x, y = pt
        d = eval_poly(x_den, x)
        if d == 0:
            return None  # kernel point -> infinity
        X = eval_poly(x_num, x) * pow(d, P - 2, P) % P
        Y = y * eval_poly(y_num, x) % P * pow(eval_poly(y_den, x),
                                              P - 2, P) % P
        return (X, Y)

    def ec_add(p1, p2, a):
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        x1, y1 = p1
        x2, y2 = p2
        if x1 == x2 and (y1 + y2) % P == 0:
            return None
        if x1 == x2:
            lam = (3 * x1 * x1 + a) * pow(2 * y1, P - 2, P) % P
        else:
            lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
        x3 = (lam * lam - x1 - x2) % P
        return (x3, (lam * (x1 - x3) - y1) % P)

    def rand_point():
        while True:
            x = random.randrange(P)
            y2v = f_of(x)
            y = pow(y2v, (P + 1) // 4, P)
            if y * y % P == y2v:
                return (x, y)

    for _ in range(4):
        pt1, pt2 = rand_point(), rand_point()
        q1, q2 = iso(pt1), iso(pt2)
        for (X, Y) in (q1, q2):
            assert Y * Y % P == (pow(X, 3, P) + 4) % P, "image not on E"
        lhs = iso(ec_add(pt1, pt2, A_PRIME))
        rhs = ec_add(q1, q2, 0)
        assert lhs == rhs, "isogeny is not a homomorphism"
    print("verified: image on E, homomorphism holds", file=sys.stderr)

    def dump(name, f):
        print(f"{name} = [")
        for c in f:
            print(f"    0x{c:096x},")
        print("]")

    dump("ISO_X_NUM", x_num)
    dump("ISO_X_DEN", x_den)
    dump("ISO_Y_NUM", y_num)
    dump("ISO_Y_DEN", y_den)


if __name__ == "__main__":
    main()
