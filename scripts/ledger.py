"""Perf-ledger CLI: diff / trend / check over BenchRecord artifacts
(obs/ledger.py) — BENCH_rNN.json, MULTICHIP_rNN.json, and any JSON tail
a bench/profile script emitted.

    python scripts/ledger.py show  BENCH_r05.json
    python scripts/ledger.py diff  BENCH_r04.json BENCH_r05.json
    python scripts/ledger.py trend BENCH_r*.json
    python scripts/ledger.py check BENCH_r*.json          # the CI gate

`trend` prints the whole trajectory with per-run deltas and flags
plateau runs; `check` exits 1 when the newest record regressed the
headline metric past --max-regression (percent) or blew a stage mean
up past --max-stage-blowup, and 0 otherwise — a trailing plateau is
printed as a flag but only fails under --fail-on-plateau (a flat curve
is a roadmap item, not a broken build).  All thresholds are PERCENT
on the CLI (5 = 5%).

Stdlib-only and device-free: safe to run in any CI lane without jax.
"""

import argparse
import json
import os
import sys

try:
    from consensus_overlord_tpu.obs import ledger
except ModuleNotFoundError:  # bare checkout: fall back to the repo root
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))
    from consensus_overlord_tpu.obs import ledger


def _fmt(v, nd=2):
    return "-" if v is None else f"{v:,.{nd}f}"


def cmd_show(args) -> int:
    rec = ledger.load_record(args.file)
    print(json.dumps(rec.to_dict(), indent=2))
    return 0


def cmd_diff(args) -> int:
    a = ledger.load_record(args.a)
    b = ledger.load_record(args.b)
    deltas = ledger.diff(a, b,
                         throughput_band=args.band / 100.0,
                         stage_band=args.stage_band / 100.0)
    if not deltas:
        print(f"{a.run} vs {b.run}: no comparable dimensions "
              "(records carry no shared numeric fields)")
        return 0
    print(f"{a.run} -> {b.run}")
    for d in deltas:
        print("  " + d.describe())
    worst = [d for d in deltas if d.verdict == "regressed"]
    print(f"{len(deltas)} dimension(s): "
          f"{sum(d.verdict == 'improved' for d in deltas)} improved, "
          f"{sum(d.verdict == 'noise' for d in deltas)} within noise, "
          f"{len(worst)} regressed")
    return 0


def cmd_trend(args) -> int:
    records = ledger.load_records(args.files)
    report = ledger.trend(records,
                          plateau_runs=args.plateau_runs,
                          plateau_band=args.plateau_band / 100.0)
    unit = next((r.unit for r in records if r.unit), "")
    # A glob can sweep a whole family of distinct metrics (the bench
    # ladder): name each rung's metric on its row and drop the single
    # trailing unit line, which would only describe one of them.
    mixed = len({(r.metric, r.unit) for r in records}) > 1
    print(f"{'run':<10} {'value':>14} {'delta%':>9} {'vs_base':>8} "
          f"{'occ':>6}  note")
    for row in report["rows"]:
        note = []
        if mixed:
            note.append(f"{row['metric']} [{row['unit'] or '-'}]")
        if row.get("plateau"):
            note.append("<- plateau")
        for k, v in (row.get("env_drift") or {}).items():
            note.append(f"env {k}: {v}")
        delta = row.get("delta_pct")
        print(f"{row['run']:<10} {_fmt(row['value']):>14} "
              f"{('%+.2f' % delta) if delta is not None else '-':>9} "
              f"{_fmt(row['vs_baseline']):>8} "
              f"{_fmt(row['occupancy']):>6}  {' | '.join(note)}")
    if unit and not mixed:
        print(f"(value unit: {unit})")
    for p in report["plateaus"]:
        print(f"PLATEAU: {p['from']} -> {p['to']} flat across {p['runs']} "
              f"runs (every delta within "
              f"+/-{report['plateau_band_pct']:.1f}%)")
    if not report["plateaus"]:
        print("no plateau in the trajectory "
              f"(band +/-{report['plateau_band_pct']:.1f}%, "
              f"min {report['plateau_runs']} runs)")
    return 0


def cmd_check(args) -> int:
    records = ledger.load_records(args.files)
    if len(records) == 1:
        # A lone record has no previous run to regress against: the
        # gate degrades to schema validation (load_record already
        # raised on garbage) and passes as a baseline — the shape CI
        # needs to gate freshly-minted per-tenant artifacts.
        cur = records[0]
        print(f"ok (baseline): {cur.run} is the first record "
              f"({cur.metric} = {_fmt(cur.value)} {cur.unit}) — "
              "nothing to compare against yet")
        return 0
    findings = ledger.check(
        records,
        max_regression=args.max_regression / 100.0,
        max_stage_blowup=args.max_stage_blowup / 100.0,
        plateau_runs=args.plateau_runs,
        plateau_band=args.plateau_band / 100.0,
        fail_on_plateau=args.fail_on_plateau)
    fatal = [f for f in findings if f.fatal]
    for f in findings:
        tag = "FAIL" if f.fatal else "FLAG"
        print(f"{tag} [{f.kind}] {f.detail}")
    cur = records[-1]
    if not findings:
        print(f"ok: {cur.run} holds the line "
              f"({cur.metric} = {_fmt(cur.value)} {cur.unit})")
    elif not fatal:
        print(f"ok (flagged): {cur.run} passes the gate")
    return 1 if fatal else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ledger",
        description="perf-ledger diff/trend/check over BenchRecord JSON")
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("show", help="normalize one artifact to the "
                       "canonical BenchRecord shape")
    p.add_argument("file")
    p.set_defaults(fn=cmd_show)

    p = sub.add_parser("diff", help="noise-banded per-dimension deltas "
                       "between two records")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--band", type=float, default=ledger.THROUGHPUT_BAND * 100,
                   help="headline-metric noise band, percent (default "
                   "%(default)s)")
    p.add_argument("--stage-band", type=float,
                   default=ledger.STAGE_BAND * 100,
                   help="stage-mean noise band, percent (default "
                   "%(default)s — stage means are few-sample and noisy)")
    p.set_defaults(fn=cmd_diff)

    for name, help_ in (("trend", "trajectory table + plateau runs"),
                        ("check", "CI gate: nonzero exit on regression "
                         "or stage blowup in the newest record")):
        p = sub.add_parser(name, help=help_)
        p.add_argument("files", nargs="+",
                       help="records in run order (BENCH_r*.json glob "
                       "order is already correct)")
        p.add_argument("--plateau-runs", type=int,
                       default=ledger.PLATEAU_RUNS,
                       help="min consecutive flat runs to flag "
                       "(default %(default)s)")
        p.add_argument("--plateau-band", type=float,
                       default=ledger.PLATEAU_BAND * 100,
                       help="flatness band, percent (default %(default)s)")
        if name == "check":
            p.add_argument("--max-regression", type=float,
                           default=ledger.MAX_REGRESSION * 100,
                           help="headline regression limit, percent "
                           "(default %(default)s)")
            p.add_argument("--max-stage-blowup", type=float,
                           default=ledger.MAX_STAGE_BLOWUP * 100,
                           help="stage-mean growth limit, percent "
                           "(default %(default)s)")
            p.add_argument("--fail-on-plateau", action="store_true",
                           help="treat a trailing plateau as fatal "
                           "(soak/owner lanes that demand progress)")
            p.set_defaults(fn=cmd_check)
        else:
            p.set_defaults(fn=cmd_trend)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
