"""Round waterfalls: reconstruct per-round timelines from the fleet
observability artifacts — the flight-recorder dump (round_flush /
straggler / alert events) joined with the device-profile ring
(stages_s + stages_at_s per staged call) on the shared `round_id` tag
(consensus_overlord_tpu/obs/fleet.py).

Each round renders as an ordered waterfall: queue-wait (from the
round_flush event's queue_wait_s) then every profiled stage —
parse → dispatch → readback → pairing on the single-chip path, plus
the sharded partial/combine stages when the mesh path ran — with
per-stage start offsets recovered from stages_at_s (completion offset)
minus stages_s (duration).  Straggler and alert events tagged with the
round ride along as annotations.

Input files are auto-detected by shape:

  * a sim/run.py or profile_verify.py JSON tail (``"profile": {"recent":
    [...]}`` — the staged-call ring, plus optional ``"flightrec"``)
  * a /statusz document (``"flightrec"`` event list + ``"profile"``)
  * a bare JSON list of flight-recorder events or ring records

Usage:
  python scripts/waterfall.py summary.json [more.json ...] [--json]
      [--rounds K] [--round ID]
  python scripts/waterfall.py --critical-path cp.json [--json]

Text rendering goes to stdout; --json instead emits one structured
document {"rounds": [...], "count": N} (the CI contract: nightly
fleet-obs-smoke asserts >= 3 reconstructed rounds from a sim summary).
Exit 0 with >= 1 round reconstructed, 4 when no round-tagged data was
found (distinct from argparse's 2).

--critical-path switches to commit-trace mode: the inputs are
``--critpath-out`` dumps from sim/run.py (or any JSON carrying the
"critpath" payload obs/causal.py exports).  Every traced height
renders as a stage waterfall with the critical (dominant-share) stage
highlighted; --json emits {"heights": [...], "count": N}.  Exit 5 when
no commit-tagged data was found (distinct from the round mode's 4).

Timelines prefer the flight recorder's monotonic `mono` stamp over the
wall-clock `ts` when both are present, so event ordering survives
clock steps during soaks.
"""

import argparse
import json
import sys

#: Commit critical-path stages in causal order (obs/causal.py STAGES).
_CRIT_STAGES = ("proposal_propagation", "router_queue_wait", "trunk_hop",
                "quorum_tail", "qc_verify", "wal_fsync", "commit")

#: Render order fallback for stages that never got a stages_at_s
#: completion offset (older ring records): the hot path's fixed order.
_STAGE_RANK = {"parse": 0, "dispatch": 1, "partial_reduce": 2,
               "allgather": 3, "readback": 4, "pairing_partial": 5,
               "pairing_combine": 6, "pairing": 7, "final_exp": 8}


def _load(path: str):
    """One artifact file → (ring_records, events)."""
    with open(path) as f:
        doc = json.load(f)
    rings, events = [], []
    if isinstance(doc, list):
        for entry in doc:
            if not isinstance(entry, dict):
                continue
            if "kind" in entry:
                events.append(entry)
            elif "stages_s" in entry:
                rings.append(entry)
        return rings, events
    if not isinstance(doc, dict):
        return rings, events
    profile = doc.get("profile")
    if isinstance(profile, dict):
        rings.extend(r for r in profile.get("recent", [])
                     if isinstance(r, dict))
    flightrec = doc.get("flightrec")
    if isinstance(flightrec, list):
        events.extend(e for e in flightrec if isinstance(e, dict))
    # statusz nests the ring under profile.recent too; a bare
    # profile-shaped dict (stage ring at top level) also works.
    if not rings and isinstance(doc.get("recent"), list):
        rings.extend(r for r in doc["recent"] if isinstance(r, dict))
    return rings, events


def _segments(record: dict):
    """One staged-call ring record → [(start_offset_s, dur_s, stage)].

    stages_at_s holds each stage's COMPLETION offset from the call's
    start; subtracting the stage duration recovers its start, so the
    waterfall shows real overlap/gaps instead of assuming stages abut.
    """
    stages = record.get("stages_s") or {}
    at = record.get("stages_at_s") or {}
    segs = []
    cursor = 0.0
    for rank, stage in enumerate(sorted(
            stages, key=lambda s: (at[s] if s in at
                                   else _STAGE_RANK.get(s, 99)))):
        dur = float(stages[stage])
        if stage in at:
            start = max(float(at[stage]) - dur, 0.0)
        else:  # legacy record: assume stages abut in rank order
            start = cursor
        cursor = start + dur
        segs.append((start, dur, stage))
    return segs


def _event_time(e: dict):
    """Ordering key for flight-recorder events: the monotonic `mono`
    stamp when present (immune to clock steps), wall-clock `ts`
    otherwise."""
    t = e.get("mono", e.get("ts"))
    return float(t) if t is not None else 0.0


def build_rounds(rings, events):
    """Join ring records + events on round_id → ordered round list."""
    rounds = {}
    events = sorted(events, key=_event_time)

    def slot(rid):
        return rounds.setdefault(rid, {
            "round_id": rid, "segments": [], "annotations": [],
            "batch": None, "queue_wait_s": None, "ops": []})

    for e in events:
        rid = e.get("round_id")
        if rid is None:
            continue
        r = slot(rid)
        if e.get("kind") == "round_flush":
            r["batch"] = e.get("batch")
            qw = e.get("queue_wait_s")
            if qw:
                r["queue_wait_s"] = float(qw)
                # Queue wait precedes every profiled stage: negative
                # offsets keep stage starts anchored at flush time 0.
                r["segments"].append(
                    {"stage": "queue_wait", "start_s": -float(qw),
                     "dur_s": float(qw)})
        else:
            r["annotations"].append(
                {k: v for k, v in e.items() if k not in ("seq",)})
    for rec in rings:
        rid = rec.get("round_id")
        if rid is None:
            continue
        r = slot(rid)
        r["ops"].append(rec.get("op"))
        if rec.get("batch") and r["batch"] is None:
            r["batch"] = rec["batch"]
        for start, dur, stage in _segments(rec):
            r["segments"].append(
                {"stage": stage, "start_s": round(start, 6),
                 "dur_s": round(dur, 6)})
    out = []
    for rid in sorted(rounds):
        r = rounds[rid]
        r["segments"].sort(key=lambda s: (s["start_s"], s["stage"]))
        if r["segments"]:
            last = max(s["start_s"] + s["dur_s"] for s in r["segments"])
            first = min(s["start_s"] for s in r["segments"])
            r["span_s"] = round(last - first, 6)
        out.append(r)
    return out


def render_text(rounds, width: int = 44) -> str:
    lines = []
    for r in rounds:
        ops = ",".join(sorted({o for o in r["ops"] if o})) or "-"
        head = (f"round {r['round_id']}  batch={r['batch'] or '-'}  "
                f"op={ops}  span={r.get('span_s', 0) * 1e3:.2f} ms")
        lines.append(head)
        segs = r["segments"]
        if not segs:
            lines.append("  (no stage data)")
            continue
        t0 = min(s["start_s"] for s in segs)
        t1 = max(s["start_s"] + s["dur_s"] for s in segs)
        span = max(t1 - t0, 1e-9)
        for s in segs:
            lead = int((s["start_s"] - t0) / span * width)
            bar = max(int(s["dur_s"] / span * width), 1)
            lines.append(f"  {s['stage']:>16s} "
                         f"{(s['start_s']) * 1e3:+9.3f} ms "
                         f"{s['dur_s'] * 1e3:9.3f} ms  "
                         f"{' ' * lead}{'#' * bar}")
        for a in r["annotations"]:
            kind = a.get("kind", "?")
            extras = " ".join(f"{k}={a[k]}" for k in a
                              if k not in ("kind", "ts", "mono",
                                           "round_id"))
            lines.append(f"  !{kind:>15s} {extras}")
        lines.append("")
    return "\n".join(lines)


def _load_traces(path: str):
    """One --critpath-out dump → list of CommitTrace dicts.  Accepts
    the full Perfetto+critpath document, a bare {"traces": [...]}
    payload, or a bare list of trace dicts."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        cp = doc.get("critpath")
        if isinstance(cp, dict):
            doc = cp
        if isinstance(doc.get("traces"), list):
            doc = doc["traces"]
    if not isinstance(doc, list):
        return []
    return [t for t in doc
            if isinstance(t, dict) and isinstance(t.get("stages"), dict)
            and "height" in t]


def build_heights(traces):
    """Group commit traces by height → ordered height list, each trace
    annotated with its critical (dominant-share) stage."""
    heights = {}
    for t in traces:
        stages = t["stages"]
        order = [s for s in _CRIT_STAGES if s in stages]
        order += sorted(s for s in stages if s not in _CRIT_STAGES)
        total = float(t.get("total_s") or sum(
            float(stages[s]) for s in order))
        shares = t.get("shares") or {}
        critical = max(order, key=lambda s: float(stages[s]),
                       default=None)
        segs, cursor = [], 0.0
        for s in order:
            dur = float(stages[s])
            segs.append({"stage": s, "start_s": round(cursor, 9),
                         "dur_s": round(dur, 9),
                         "share": round(float(shares.get(
                             s, dur / total if total > 0 else 0.0)), 6),
                         "critical": s == critical})
            cursor += dur
        heights.setdefault(int(t["height"]), []).append({
            "node": t.get("node", "?"), "round": t.get("round", 0),
            "total_s": total, "via_trunk": bool(t.get("via_trunk")),
            "path": t.get("path", "commit"),
            "critical": critical, "segments": segs})
    return [{"height": h, "traces": heights[h]}
            for h in sorted(heights)]


def render_critpath(heights, width: int = 44) -> str:
    lines = []
    for entry in heights:
        lines.append(f"height {entry['height']}")
        for t in entry["traces"]:
            trunk = "  via_trunk" if t["via_trunk"] else ""
            lines.append(f"  node {t['node'][:8]}  round {t['round']}  "
                         f"total={t['total_s'] * 1e3:.3f} ms{trunk}")
            span = max(t["total_s"], 1e-9)
            for s in t["segments"]:
                lead = int(s["start_s"] / span * width)
                bar = max(int(s["dur_s"] / span * width), 1)
                mark = "*" if s["critical"] else " "
                lines.append(
                    f"  {mark} {s['stage']:>20s} "
                    f"{s['dur_s'] * 1e3:9.3f} ms {s['share'] * 100:5.1f}%  "
                    f"{' ' * lead}{'#' * bar}")
        lines.append("")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="reconstruct per-round stage waterfalls from "
                    "flightrec + profile-ring artifacts")
    ap.add_argument("files", nargs="+",
                    help="JSON artifacts (sim summary, statusz doc, or "
                    "bare event/ring lists)")
    ap.add_argument("--json", action="store_true",
                    help="emit the structured timeline document instead "
                    "of the text rendering")
    ap.add_argument("--rounds", type=int, default=None, metavar="K",
                    help="render only the last K rounds")
    ap.add_argument("--round", type=int, default=None, metavar="ID",
                    help="render only this round_id")
    ap.add_argument("--critical-path", action="store_true",
                    help="commit-trace mode: inputs are --critpath-out "
                    "dumps; render per-height stage waterfalls with "
                    "the critical stage highlighted")
    args = ap.parse_args()

    if args.critical_path:
        traces = []
        for path in args.files:
            traces.extend(_load_traces(path))
        heights = build_heights(traces)
        if args.json:
            print(json.dumps({"heights": heights,
                              "count": len(heights),
                              "traces": len(traces)}))
        else:
            print(render_critpath(heights))
            print(f"heights: {len(heights)}  traces: {len(traces)}")
        if not heights:
            print("no commit-tagged data found", file=sys.stderr)
            return 5
        return 0

    rings, events = [], []
    for path in args.files:
        r, e = _load(path)
        rings.extend(r)
        events.extend(e)
    rounds = build_rounds(rings, events)
    if args.round is not None:
        rounds = [r for r in rounds if r["round_id"] == args.round]
    if args.rounds is not None:
        rounds = rounds[-args.rounds:]
    if args.json:
        print(json.dumps({"rounds": rounds, "count": len(rounds)}))
    else:
        print(render_text(rounds))
        print(f"rounds: {len(rounds)}  ring_records: {len(rings)}  "
              f"events: {len(events)}")
    if not rounds:
        print("no round-tagged data found", file=sys.stderr)
        return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
