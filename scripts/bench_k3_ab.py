"""A/B the k=3 fused-kernel rung against padding 3-hash batches to k=4.

The r4 commit 349fab5 added a dedicated 3-group rung to the fused
multi-hash verify ladder (tpu_provider._GROUP_SIZES), justified by MSM
op count alone (1 G1 + 3 G2 MSMs vs 1 + 4, expected ~+25%) — the exact
style of reasoning that measured wrong three times in this project
(Pippenger r3, staircase r4, G2 tables r4).  This script supplies the
measurement: interleaved A/B of the SAME 3-distinct-hash batch stream
through the k=3 kernel vs the k=4 kernel (same provider, same pubkey
cache, same day), pipelined at the production depth.

Per the BASELINE.md r3 honesty note, the remote PJRT relay dedupes
repeated identical computations — defeated here (as in bench.py) by the
fresh per-call RLC weights verify_batch draws internally.

Usage: python scripts/bench_k3_ab.py [N] [segments]
Prints per-segment rates and the final k=3/k=4 throughput ratio.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

N = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
SEGMENTS = int(sys.argv[2]) if len(sys.argv) > 2 else 3  # per arm
DEPTH = int(os.environ.get("BENCH_DEPTH", "8"))
DISPATCHES = 3 * DEPTH  # sustained-pipeline dispatch count (bench.py r4)


def run_segment(provider, sigs, hashes, pks):
    t0 = time.time()
    inflight = []
    done = 0
    ok = True
    for _ in range(DISPATCHES):
        inflight.append(provider.verify_batch_async(sigs, hashes, pks))
        if len(inflight) >= DEPTH:
            ok &= all(inflight.pop(0)())
            done += 1
    while inflight:
        ok &= all(inflight.pop(0)())
        done += 1
    rate = N * done / (time.time() - t0)
    assert ok, "batch failed verification"
    return rate


def main():
    from consensus_overlord_tpu.compile_cache import enable
    enable()

    os.environ["BENCH_HASHES"] = "3"  # before import: bench derives
    import bench                      # HASHES and the fixture name from it
    from consensus_overlord_tpu.crypto import tpu_provider as tp

    bench.N = N
    sigs, hashes, pks = bench._fixture()
    assert len({bytes(h) for h in hashes}) == 3

    provider = tp.TpuBlsCrypto(0xA11CE)
    provider.update_pubkeys(pks)

    arms = {"k3": (2, 3, 4), "k4": (2, 4)}
    # Warm both kernels (compile) before any timing.
    for name, sizes in arms.items():
        tp._GROUP_SIZES = sizes
        t0 = time.time()
        assert all(provider.verify_batch(sigs, hashes, pks))
        print(f"warm {name}: {time.time() - t0:.1f}s", flush=True)

    rates = {"k3": [], "k4": []}
    for seg in range(SEGMENTS):
        for name, sizes in arms.items():
            tp._GROUP_SIZES = sizes
            r = run_segment(provider, sigs, hashes, pks)
            rates[name].append(r)
            print(f"seg {seg} {name}: {r:,.0f} verifies/s", flush=True)

    best3, best4 = max(rates["k3"]), max(rates["k4"])
    med3 = sorted(rates["k3"])[len(rates["k3"]) // 2]
    med4 = sorted(rates["k4"])[len(rates["k4"]) // 2]
    print(f"k3 best/median: {best3:,.0f} / {med3:,.0f}", flush=True)
    print(f"k4 best/median: {best4:,.0f} / {med4:,.0f}", flush=True)
    print(f"k3/k4 median ratio: {med3 / med4:.3f}x", flush=True)


if __name__ == "__main__":
    main()
