"""Round-shaped open-loop driver: consensus-round latency at 1k/4k/10k
validators on the device path — the driver's second metric, measured at
its stated scale (BASELINE.md "consensus-round p50 latency @ 1k
validators"; r4 verdict Missing #1).

Running N full Python engines saturates a 1-2 vCPU host at N≈256 and
measures the router, not the round (BASELINE.md config-2 row).  What the
metric actually describes is the LEADER's round: an O(N) flood of signed
votes in, one QC broadcast out (reference src/consensus.rs:397-463 — the
per-vote verify stream plus the aggregate).  So this driver runs exactly
ONE production engine as the round leader:

  N-1 pre-signed PREVOTE votes (fixture-cached, like bench.py) are
  injected through engine.inject_inbound → the batching frontier
  coalesces them into device-sized verify_round batches → the engine
  counts weights → at 2N/3 it aggregates the QC on device and
  broadcasts.  Wall-clock runs from the first vote injected to the
  MSG_TYPE_AGGREGATED_VOTE broadcast leaving the adapter.

The follower side — QC aggregate verification (bitmap extraction +
device pubkey-sum + host pairing) — is timed separately over the QC the
leader produced, since every non-leader pays that cost once per round.

Everything in the measured path is production code: Engine._on_signed_vote,
BatchingVerifier, TpuBlsCrypto.  The only bench-only liberties: the
leader schedule is pinned to this engine (leader() monkeypatch — vote
floods for rounds this node doesn't lead would just be dropped), WAL is
the in-memory twin (host fsync noise is not the metric), and votes are
injected in one burst (open loop) rather than trickling over network
sockets.

CONSENSUS_PAD_MIN=2048 pins the frontier's batch rungs to one kernel
shape (the same knob production deployments use, BASELINE.md r4 notes).

Usage: python scripts/bench_round.py [N] [ROUNDS] [--mesh D]
Emits one JSON line per scale with p50/p95, first-touch round, frontier
batch stats, and follower QC-verify p50.  --mesh D runs the leader's
provider over a D-lane virtual CPU mesh (forces the CPU platform; the
device-count flag must precede jax's backend init, which is why it is
parsed at module level) and emits the metric as mesh_round_p50_ms so
the mesh rung trends as its own ledger family.
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("CONSENSUS_PAD_MIN", "2048")
# One pubkey-cache capacity across the 1k/4k/10k scales → the verify and
# QC kernels keep ONE shape set (each fresh capacity is a full kernel
# recompile, ~30-60 min through the remote-compile tunnel).
os.environ.setdefault("CONSENSUS_PK_CAP_MIN", "16384")

# Comma-separated scales run in ONE process, largest fixture shared:
# TPU-tunnel kernels are never persistently cached (executable
# serialization is unsupported through the relay), so per-scale
# processes would each re-pay the full kernel-set compile.
MESH = int(sys.argv[sys.argv.index("--mesh") + 1]) \
    if "--mesh" in sys.argv else 0
if MESH:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            f"{_flags} --xla_force_host_platform_device_count={MESH}"
        ).strip()
_pos = [a for a in sys.argv[1:] if not a.startswith("-")
        and a != (sys.argv[sys.argv.index("--mesh") + 1]
                  if "--mesh" in sys.argv else None)]
SCALES = [int(x) for x in _pos[0].split(",")] if _pos else [1000]
ROUNDS = int(_pos[1]) if len(_pos) > 1 else 20
CONTENT = b"bench-round-block"


def fixture(n: int):
    """n keypairs + n signed PREVOTE votes on one block hash (sks are a
    fixed arithmetic sequence, so a smaller fixture is a prefix of a
    larger one).  Signing is host-side pure Python (~10 ms/vote) —
    cached to disk because setup cost is not the thing under test."""
    import numpy as np

    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.core.types import Vote, VoteType
    from consensus_overlord_tpu.crypto import bls12381 as oracle

    # Cached under scripts/.cache (gitignored), NOT the repo root — bench
    # fixtures are regenerable artifacts, not working-tree clutter.
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".cache")
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, f"round_fixture{n}.npz")
    block_hash = sm3_hash(CONTENT)
    vote = Vote(1, 0, VoteType.PREVOTE, block_hash)
    vote_hash = sm3_hash(vote.encode())
    if os.path.exists(path):
        data = np.load(path)
        pks = [bytes(r) for r in data["pks"]]
        sigs = [bytes(r) for r in data["sigs"]]
        return pks, sigs, vote, vote_hash
    sks = [0xF00D + 131 * i for i in range(n)]
    t0 = time.time()
    pks = [oracle.sk_to_pk(sk) for sk in sks]
    sigs = [oracle.sign(sk, vote_hash) for sk in sks]
    print(f"fixture: signed {n} votes in {time.time() - t0:.0f}s",
          file=sys.stderr, flush=True)
    np.savez(path,
             pks=np.frombuffer(b"".join(pks), np.uint8).reshape(n, 96),
             sigs=np.frombuffer(b"".join(sigs), np.uint8).reshape(n, 48))
    return pks, sigs, vote, vote_hash


class _Adapter:
    """Chain adapter stub: serves the fixture block, captures broadcasts."""

    def __init__(self, block_hash):
        self._block_hash = block_hash
        self.qc_event = asyncio.Event()
        self.qc_payload = None
        self.t_qc = None

    async def get_block(self, height):
        return CONTENT, self._block_hash

    async def check_block(self, height, block_hash, content):
        return True

    async def commit(self, height, commit):
        return None

    async def get_authority_list(self, height):
        return []

    async def broadcast_to_other(self, msg_type, payload):
        if msg_type == "AggregatedVote" and not self.qc_event.is_set():
            self.t_qc = time.perf_counter()
            self.qc_payload = payload
            self.qc_event.set()

    async def transmit_to_relayer(self, relayer, msg_type, payload):
        pass

    def report_error(self, context):
        pass

    def report_view_change(self, height, round_, reason):
        pass


async def one_round(provider, pks, sigs, vote, rep, metrics=None):
    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.core.types import Node, SignedVote
    from consensus_overlord_tpu.crypto.frontier import BatchingVerifier
    from consensus_overlord_tpu.engine.smr import Engine
    from consensus_overlord_tpu.engine.wal import MemoryWal

    authorities = [Node(pk) for pk in pks]
    adapter = _Adapter(sm3_hash(CONTENT))
    frontier = BatchingVerifier(provider, max_batch=2048, linger_s=0.005,
                                metrics=metrics)
    eng = Engine(pks[0], adapter, provider, MemoryWal(metrics=metrics),
                 frontier=frontier, metrics=metrics)
    eng.leader = lambda h, r: eng.name  # pin the leader schedule (see module doc)
    # Huge interval: phase timers must sit far beyond any first-touch
    # kernel compile absorbed by rep 0 (a mid-compile PROPOSE timeout
    # would move the engine off round 0 and muddy the rep).
    run_task = asyncio.create_task(
        eng.run(1, 7_200_000, authorities))
    await asyncio.sleep(0)  # let the engine enter round 0

    votes = [SignedVote(pks[i], sigs[i], vote) for i in range(1, len(pks))]
    t0 = time.perf_counter()
    inject = [asyncio.create_task(eng.inject_inbound(sv)) for sv in votes]
    await adapter.qc_event.wait()
    dt = adapter.t_qc - t0
    eng.stop()
    await run_task
    await asyncio.gather(*inject, return_exceptions=True)
    frontier.close()
    st = frontier.stats
    assert adapter.qc_payload is not None and st.failures == 0, (
        f"round {rep}: {st.failures} frontier failures")
    return dt, adapter.qc_payload, st


async def follower_verify(provider, authorities, qc_payload):
    """One follower's QC check, the production _verify_qc shape: decode,
    bitmap → voters, device pubkey aggregation + host pairing."""
    from consensus_overlord_tpu.core.bitmap import extract_voters
    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.core.types import AggregatedVote

    t0 = time.perf_counter()
    qc = AggregatedVote.decode(qc_payload)
    voters = extract_voters(authorities, qc.signature.address_bitmap)
    vote_hash = sm3_hash(qc.to_vote().encode())
    resolve = provider.verify_aggregated_async(
        qc.signature.signature, vote_hash, voters)
    ok = await asyncio.to_thread(resolve)
    assert ok, "follower QC verification failed"
    return time.perf_counter() - t0, len(voters)


def pctl(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


async def main():
    if MESH or os.environ.get("CONSENSUS_BENCH_CPU"):  # smoke lane: the
        import jax                             # axon plugin pins
        jax.config.update("jax_platforms", "cpu")  # JAX_PLATFORMS; the
        # config override wins (and the virtual mesh is CPU-only)
    from consensus_overlord_tpu.compile_cache import enable
    enable()
    from consensus_overlord_tpu.core.types import Node
    from consensus_overlord_tpu.crypto.tpu_provider import TpuBlsCrypto

    n_max = max(SCALES)
    pks, sigs, vote, vote_hash = fixture(n_max)
    mesh = None
    if MESH:
        from consensus_overlord_tpu.parallel import make_mesh

        mesh = make_mesh(MESH)
        print(f"mesh: {mesh.devices.size} lanes", file=sys.stderr,
              flush=True)
    provider = TpuBlsCrypto(0xF00D, device_threshold=32, mesh=mesh)

    # One fill for the whole run (smaller scales use a row prefix),
    # chunked to the pad floor so pubkey validation compiles ONE kernel
    # shape instead of one per scale.
    chunk = int(os.environ["CONSENSUS_PAD_MIN"])
    t0 = time.time()
    for i in range(0, n_max, chunk):
        provider.update_pubkeys(pks[i:i + chunk])
    t_pk = time.time() - t0
    print(f"pubkey validate+cache ({n_max}): {t_pk:.1f}s", file=sys.stderr,
          flush=True)

    for n in SCALES:
        # Fresh registry per scale: the emitted histograms describe THIS
        # scale's batch shape, not a mix across the sweep.  The provider
        # binds to it too (dispatch-phase split: prep/dispatch/readback/
        # pairing).
        from consensus_overlord_tpu.obs import (DeviceProfiler, Metrics,
                                                snapshot)
        metrics = Metrics()
        prof = DeviceProfiler(metrics)
        provider.bind_metrics(None)  # rep 0 (compiles) runs unmetered
        provider.bind_profiler(None)

        lat, fstats = [], []
        qc_payload = None
        # rep 0 absorbs first-touch compiles for this scale's rungs and
        # is reported separately — it runs unmetered (a compile-inflated
        # dispatch phase would dominate every histogram).
        for rep in range(ROUNDS + 1):
            dt, qc_payload, st = await one_round(
                provider, pks[:n], sigs[:n], vote, rep,
                metrics=metrics if rep > 0 else None)
            if rep == 0:
                provider.bind_metrics(metrics)  # compiles are done now
                provider.bind_profiler(prof)
                first = dt
            else:
                lat.append(dt)
                fstats.append(st)
            print(f"  [{n}] round {rep}: {dt * 1e3:8.1f} ms  "
                  f"(batches {st.batches}, mean {st.mean_batch:.0f}, "
                  f"max {st.max_batch})", file=sys.stderr, flush=True)

        authorities = [Node(pk) for pk in pks[:n]]
        fv = []
        for rep in range(ROUNDS + 1):
            dt, q = await follower_verify(provider, authorities, qc_payload)
            if rep:
                fv.append(dt)
            print(f"  [{n}] follower verify {rep}: {dt * 1e3:8.1f} ms "
                  f"({q} voters)", file=sys.stderr, flush=True)

        batches = [s.batches for s in fstats]
        # Registry scrape: the frontier/device histograms (batch sizes,
        # occupancy, queue wait, dispatch phases, round durations) ride
        # along in the BENCH_* JSON so batch-shape drift is visible in
        # the ledger, not just the p50s.
        shape = snapshot(metrics.registry, prefix="frontier")
        shape.update(snapshot(metrics.registry, prefix="crypto_dispatch"))
        shape.update(snapshot(metrics.registry, prefix="consensus_round"))
        shape.update(snapshot(metrics.registry, prefix="crypto_device"))
        from consensus_overlord_tpu.obs import ledger

        # Ledger envelope (schema version + env fingerprint): the
        # per-scale line lands in BENCH_* artifacts and must
        # diff/trend like bench.py's record.
        print(json.dumps(ledger.annotate({
            # The mesh rung is its own ledger family — see bench.py.
            "metric": ("mesh_round_p50_ms" if MESH
                       else "consensus_round_p50_ms"),
            "validators": n, "mesh_devices": MESH,
            # Headline value/unit: the ledger's diff/check gates on
            # these (unit "ms" marks the metric lower-is-better).
            "value": round(pctl(lat, 0.5) * 1e3, 1), "unit": "ms",
            "rounds": ROUNDS,
            "leader_p50_ms": round(pctl(lat, 0.5) * 1e3, 1),
            "leader_p95_ms": round(pctl(lat, 0.95) * 1e3, 1),
            "leader_first_touch_ms": round(first * 1e3, 1),
            "follower_qc_verify_p50_ms": round(pctl(fv, 0.5) * 1e3, 1),
            "frontier_batches_per_round":
                round(sum(batches) / len(batches), 1),
            "pubkey_cache_fill_s": round(t_pk, 1),
            "metrics": shape,
            # Staged device profile (obs/prof.py): per-op stage split +
            # last-batch occupancy — the per-chip view of where the
            # leader's round actually went.
            "profile": {**prof.summary(), "recent": prof.tail(8)},
        })), flush=True)


if __name__ == "__main__":
    asyncio.run(main())
