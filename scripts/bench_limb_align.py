"""The r4 verdict's named single-chip lever: elementwise lane ALIGNMENT.

bench_field_radix.py (r4) measured the production field multiply at
99.5 GMAC/s of useful conv MACs — ~47% of the chip's practical int32
elementwise ceiling — and attributed the gap to padding: with the limb
axis MINOR, every (B, 39) / (B, 77) op occupies a full 128-lane vector
register row, wasting 70% / 40% of each tile.  The hypothesis here: put
the BATCH on the lane axis (minor, 8192 = 64 full tiles) and the limb
axis on sublanes (39 → 5 sublane-tiles, 4% pad), so op cost scales with
the true limb width instead of rounding to 128.

Measured chains (slope-timed dependent chains per bench_field_radix.py's
honesty rules — fresh salt per call, one checksum download, per-step =
(t(2K) − t(K)) / K so the ~120-200 ms PJRT-tunnel round-trip cancels):

  1. FQ.mul, current (B, n) layout              [production baseline]
  2. transposed (n, B) mul: same op sequence, conv + identical reduce
     plan on axis -2; bit-identical outputs (asserted)
  3. decomposition of 1: conv alone vs reduce alone (which half owns
     the time decides where further levers live)

Usage: python scripts/bench_limb_align.py [B] [K]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from consensus_overlord_tpu.compile_cache import enable

enable()
from consensus_overlord_tpu.ops.field import BLS12_381_FQ as FQ

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
K = int(sys.argv[2]) if len(sys.argv) > 2 else 64
ITERS = 6
rng = np.random.default_rng(11)
n = FQ.n
b_bits = FQ.b
mask = FQ.mask


def timed(name, make_chain, *arrays, macs_per_step=None):
    devs = [jnp.asarray(a) for a in arrays]

    def median_call(fn):
        ts = []
        for i in range(ITERS + 1):
            t0 = time.time()
            jax.device_get(fn(*devs, jnp.int32(i)))
            ts.append(time.time() - t0)
        return sorted(ts[1:])[len(ts[1:]) // 2]

    t1 = median_call(jax.jit(make_chain(K)))
    t2 = median_call(jax.jit(make_chain(2 * K)))
    per_step = max((t2 - t1) / K, 1e-9)
    extra = ""
    if macs_per_step:
        extra = f"  ({macs_per_step / per_step / 1e9:6.1f} GMAC/s)"
    print(f"  {name:<44s} {per_step * 1e6:9.1f} us/step{extra}"
          f"   [K call {t1 * 1e3:.0f} ms, 2K {t2 * 1e3:.0f} ms]",
          flush=True)
    return per_step


# -- transposed (limb-major, batch-minor) formulation -----------------------

def reduce_T(x, bounds):
    """FQ._reduce with the position axis at -2 — the identical statically
    planned step sequence, so values match the production path bit for
    bit."""
    for step, arg in FQ._plan(list(bounds)):
        if step == "pad":
            x = jnp.concatenate(
                [x, jnp.zeros(x.shape[:-2] + (arg, x.shape[-1]), jnp.int32)],
                axis=-2)
        elif step == "fold":
            lo, hi = x[..., :n, :], x[..., n:, :]
            x = lo + jnp.einsum("...kb,kj->...jb", hi, FQ._fold[:arg])
        else:  # carry
            if arg:
                x = jnp.concatenate(
                    [x, jnp.zeros(x.shape[:-2] + (1, x.shape[-1]),
                                  jnp.int32)], axis=-2)
            c = x >> b_bits
            x = (x & mask) + jnp.concatenate(
                [jnp.zeros(x.shape[:-2] + (1, x.shape[-1]), jnp.int32),
                 c[..., :-1, :]], axis=-2)
    return x


def mul_T(x, y):
    """Product convolution with limbs on axis -2, batch minor."""
    terms = [
        jnp.pad(x[..., i:i + 1, :] * y,
                [(0, 0)] * (y.ndim - 2) + [(i, n - 1 - i), (0, 0)])
        for i in range(n)
    ]
    out = terms[0]
    for t in terms[1:]:
        out = out + t
    return reduce_T(out, FQ._conv_bounds())


def main():
    print(f"backend={jax.default_backend()} B={B} K={K} n={n}", flush=True)
    # loose_max − 8: headroom for the raw +salt seeds (salt ≤ ITERS).
    yl = rng.integers(0, FQ.loose_max - 8, (B, n), dtype=np.int32)
    fmac = B * n * n

    # Bit-identical check first (CPU-cheap shapes).
    xs = rng.integers(0, FQ.loose_max + 1, (256, n), dtype=np.int32)
    ys = rng.integers(0, FQ.loose_max + 1, (256, n), dtype=np.int32)
    a = jax.device_get(jax.jit(FQ.mul)(jnp.asarray(xs), jnp.asarray(ys)))
    bt = jax.device_get(jax.jit(mul_T)(jnp.asarray(xs.T), jnp.asarray(ys.T)))
    assert np.array_equal(a, bt.T), "transposed mul drifts from production"
    print("  bit-identical: mul_T(x.T, y.T).T == FQ.mul(x, y)", flush=True)

    def chain_cur(length):
        def fn(y, salt):
            def step(c, _):
                return FQ.mul(c, y), None
            c, _ = lax.scan(step, FQ.add(y, jnp.broadcast_to(salt, y.shape)),
                            None, length=length)
            return FQ.strict(c).sum()
        return fn

    def chain_T(length):
        def fn(y, salt):
            yT = y.T  # boundary transpose, amortized over the chain
            def step(c, _):
                return mul_T(c, yT), None
            # salt UNREDUCED into the seed (y is drawn loose_max-8 so
            # bounds hold): every call must be a distinct computation or
            # the PJRT relay dedups it to the link floor — the first run
            # of this script used salt%3 and "measured" 0 us/step.
            c, _ = lax.scan(step, yT + salt, None, length=length)
            return c.sum()
        return fn

    # conv-only / reduce-only decomposition (cost diagnostics, not field
    # math: conv-only truncates + masks to stay bounded, reduce-only
    # rebuilds a width-(2n-1) input from the running value).
    def chain_conv(length):
        def fn(y, salt):
            def step(c, _):
                terms = [
                    jnp.pad(c[..., i:i + 1] * y,
                            [(0, 0)] * (y.ndim - 1) + [(i, n - 1 - i)])
                    for i in range(n)
                ]
                out = terms[0]
                for t in terms[1:]:
                    out = out + t
                # Fold the high half back cheaply so no partial product
                # is dead code (a plain [:n] truncation lets XLA DCE
                # every MAC landing at positions >= n).
                hi = jnp.pad(out[..., n:],
                             [(0, 0)] * (y.ndim - 1) + [(0, 1)])
                return (out[..., :n] + hi) & mask, None
            c, _ = lax.scan(step, y + salt, None, length=length)
            return c.sum()
        return fn

    def chain_reduce(length):
        def fn(y, salt):
            def step(c, _):
                wide = jnp.concatenate([c, c[..., :n - 1]], axis=-1)
                return FQ._reduce(wide, FQ._conv_bounds()), None
            c, _ = lax.scan(step, (y + salt) & mask, None, length=length)
            return c.sum()
        return fn

    print(f"-- full field-mul chains, B={B} --", flush=True)
    t_cur = timed("(B,n) limb-minor (production)", chain_cur, yl,
                  macs_per_step=fmac)
    t_T = timed("(n,B) limb-on-sublanes, batch-minor", chain_T, yl,
                macs_per_step=fmac)
    print(f"-- decomposition (current layout) --", flush=True)
    t_cv = timed("conv only (trunc+mask)", chain_conv, yl, macs_per_step=fmac)
    t_rd = timed("reduce only (rebuilt wide input)", chain_reduce, yl)
    print("-- summary --", flush=True)
    print(f"  transposed/current {t_T / t_cur:.2f}x  "
          f"conv share ~{t_cv / t_cur:.2f}  reduce share ~{t_rd / t_cur:.2f}",
          flush=True)

    # Self-contained ledger tail: this rung's own metric, never mixed
    # into the BLS headline trend.  Headline > 1 means the transposed
    # (limb-on-sublanes) layout beats production.
    import json

    from consensus_overlord_tpu.obs import ledger
    print(json.dumps(ledger.build_record(
        "ladder_limb_align_transposed_speedup", round(t_cur / t_T, 4), "x",
        context={"backend": jax.default_backend(), "batch": B, "chain": K,
                 "current_us_per_step": round(t_cur * 1e6, 2),
                 "transposed_us_per_step": round(t_T * 1e6, 2),
                 "conv_share": round(t_cv / t_cur, 3),
                 "reduce_share": round(t_rd / t_cur, 3)})))


if __name__ == "__main__":
    main()
