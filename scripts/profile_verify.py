"""Break down verify_batch wall time into stages on the real device.

Usage:  python scripts/profile_verify.py [N]

Stages timed separately (each with block_until_ready):
  parse      — host parse of N compressed G1 sigs
  round      — the fused device kernel (G1 validate+MSM, pubkey-cache
               gather + G2 MSM) INCLUDING the H2D upload + dispatch
  readback   — device_get of the round outputs
  pairing    — host 2-pairing batch check (native backend if built)
  full       — end-to-end provider.verify_batch
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1024


def timeit(label, fn, iters=4):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    dt = (time.perf_counter() - t0) / iters
    print(f"{label:12s} {dt * 1e3:9.2f} ms", flush=True)
    return out, dt


def main():
    from consensus_overlord_tpu.compile_cache import enable
    enable()
    import jax.numpy as jnp

    from consensus_overlord_tpu.crypto import bls12381 as oracle
    from consensus_overlord_tpu.crypto import tpu_provider as tp
    from consensus_overlord_tpu.ops import bls12381_groups as dev

    print(f"device: {jax.devices()[0].platform}  N={N}", flush=True)
    # Reuse bench.py's fixture (same cache file + message) so the two
    # tools can never drift apart on what they measure.
    import bench
    bench.N = N
    sigs, h, pks = bench._fixture()

    provider = tp.TpuBlsCrypto(0xA11CE)
    provider.update_pubkeys(pks)

    parsed, _ = timeit("parse", lambda: dev.parse_g1_compressed(sigs))
    prep, _ = timeit("host_prep", lambda: provider._host_prep(sigs, pks, N))

    def round_blocked():
        out = provider._kernels.verify_round(
            jnp.asarray(prep[1]), jnp.asarray(prep[2]), jnp.asarray(prep[3]),
            jnp.asarray(prep[4]), jnp.asarray(prep[5]), jnp.asarray(prep[6]),
            *provider._pk_device())
        jax.block_until_ready(out)
        return out

    out, _ = timeit("round", round_blocked)
    timeit("readback", lambda: jax.device_get(out))

    ax, ay, ainf, valid, gx, gy, ginf = jax.device_get(out)
    agg_sig = tp._affine_to_oracle_g1(ax, ay, ainf)
    agg_pk = tp._affine_to_oracle_g2(gx, gy, ginf)
    h_pt = oracle.hash_to_g1(h, b"")
    neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
    timeit("pairing", lambda: oracle.multi_pairing_is_one(
        [(agg_sig, neg_g2), (h_pt, agg_pk)]))
    timeit("hash_to_g1", lambda: oracle.hash_to_g1(h, b""))

    _, full_dt = timeit("full", lambda: provider.verify_batch(
        sigs, [h] * N, pks), iters=2)
    print(f"rate: {N / full_dt:.0f} verifies/s", flush=True)


if __name__ == "__main__":
    main()
