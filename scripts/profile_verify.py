"""Break down verify_batch wall time into stages on the real device.

Usage:  python scripts/profile_verify.py [N]

Stages timed separately (each with block_until_ready):
  parse      — host parse of N compressed G1 sigs
  g1_msm     — device decompress+validate+RLC-MSM over signatures
  g2_msm     — device RLC-MSM over cached pubkey rows
  pairing    — host 2-pairing batch check (native backend if built)
  full       — end-to-end provider.verify_batch
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1024


def timeit(label, fn, iters=4):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    dt = (time.perf_counter() - t0) / iters
    print(f"{label:12s} {dt * 1e3:9.2f} ms")
    return out, dt


def main():
    from consensus_overlord_tpu.compile_cache import enable
    enable()
    import jax.numpy as jnp

    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto import bls12381 as oracle
    from consensus_overlord_tpu.crypto import tpu_provider as tp
    from consensus_overlord_tpu.ops import bls12381_groups as dev

    print(f"device: {jax.devices()[0].platform}  N={N}")
    # Reuse bench.py's fixture (same cache file + message) so the two
    # tools can never drift apart on what they measure.
    import bench
    bench.N = N
    sigs, h, pks = bench._fixture()

    provider = tp.TpuBlsCrypto(0xA11CE)
    provider.update_pubkeys(pks)

    parsed, _ = timeit("parse", lambda: dev.parse_g1_compressed(sigs))
    size = provider._pad_to(N)

    x = np.zeros((size, dev.FQ.n), np.int32)
    x[:N] = parsed.x
    sgn = np.zeros(size, bool)
    sgn[:N] = parsed.sign
    inf = np.zeros(size, bool)
    ok = np.zeros(size, bool)
    ok[:N] = parsed.wellformed
    bits = np.zeros((size, tp._SCALAR_BITS), np.int32)
    bits[:N] = np.unpackbits(
        np.frombuffer(os.urandom(N * tp._SCALAR_BITS // 8), np.uint8)
        .reshape(N, -1), axis=1)

    def g1():
        out = provider._kernels.g1_validate_msm(
            jnp.asarray(x), jnp.asarray(sgn), jnp.asarray(inf),
            jnp.asarray(ok), jnp.asarray(bits))
        jax.block_until_ready(out)
        return out

    (ax, ay, ainf, valid), g1_dt = timeit("g1_msm", g1)

    rows = provider._pk_rows_of(pks)
    pad_rows = np.zeros(size, np.int64)
    pad_rows[:N] = rows
    px, py, pz = (provider._pk_px[pad_rows], provider._pk_py[pad_rows],
                  provider._pk_pz[pad_rows])

    def g2():
        out = provider._kernels.g2_msm(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(pz),
            jnp.asarray(bits))
        jax.block_until_ready(out)
        return out

    (gax, gay, gainf), g2_dt = timeit("g2_msm", g2)

    agg_sig = tp._affine_to_oracle_g1(ax, ay, ainf)
    agg_pk = tp._affine_to_oracle_g2(gax, gay, gainf)
    h_pt = oracle.hash_to_g1(h, b"")
    neg_g2 = (oracle.G2_GEN[0], oracle.fq2_neg(oracle.G2_GEN[1]))
    timeit("pairing", lambda: oracle.multi_pairing_is_one(
        [(agg_sig, neg_g2), (h_pt, agg_pk)]))
    timeit("hash_to_g1", lambda: oracle.hash_to_g1(h, b""))

    _, full_dt = timeit("full", lambda: provider.verify_batch(
        sigs, [h] * N, pks), iters=2)
    print(f"rate: {N / full_dt:.0f} verifies/s")


if __name__ == "__main__":
    main()
