"""Stage-by-stage breakdown of the device verify pipeline, on the real
device or CPU lanes — built on the permanent profiling layer
(consensus_overlord_tpu/obs/prof.py) instead of ad-hoc timers, so what
this script reports is exactly what production exports as
`crypto_device_stage_seconds{stage,op}` / the /statusz "profile" ring.

Stages (each boundary bounded by block_until_ready, recorded by the
provider's own instrumentation):
  parse      — host prep of N compressed G1 sigs (parse/pad/RLC draw)
  dispatch   — the fused round kernel enqueue (G1 validate+MSM,
               pubkey-cache gather + G2 MSM) incl. the H2D upload
  readback   — device_get of the round outputs
  pairing    — host 2-pairing batch check (native backend if built)

--sharded-probe adds the mesh stage split (per-device partial reduce vs
ICI all-gather, plus the pairing partial-vs-combine split,
TpuBlsCrypto.profile_sharded_stages); --profile-dir captures an XLA
trace of one measured batch through ProfileSession.  --mesh D profiles
the provider's MESH kernel set over a D-lane virtual CPU mesh
(--xla_force_host_platform_device_count — set before jax initializes,
which is why this script imports jax only inside main), so the
device-pairing + sharded numbers come from the production mesh path.

Usage:  python scripts/profile_verify.py [N] [--iters K] [--json]
            [--cpu] [--mesh D] [--sharded-probe] [--profile-dir DIR]

Emits one {"metric": ...} JSON line on stdout (the bench_round.py
contract; human-readable stage lines go to stderr), so CI can smoke-run
it on CPU lanes and ledger the output.  N defaults to 1024 on an
accelerator and 8 on CPU (a 1024-lane kernel compile is minutes of CPU
LLVM time and profiles nothing the 8-lane rung doesn't).
"""

import argparse
import json
import sys
import time

try:
    import consensus_overlord_tpu  # noqa: F401 — the installed package
except ModuleNotFoundError:  # bare checkout: fall back to the repo root
    import os

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))


def _fixture(n: int):
    """N (sig, hash, pubkey) triples on one message hash.  Reuses
    bench.py's disk-cached fixture when the repo root is importable
    (same cache file + message, so the two tools can't drift apart);
    otherwise rebuilds with bench's exact key schedule."""
    try:
        import bench

        bench.N, bench.HASHES = n, 1
        sigs, hashes, pks = bench._fixture()
        return sigs, hashes[0], pks
    except ModuleNotFoundError:  # installed package, no repo checkout
        from consensus_overlord_tpu.core.sm3 import sm3_hash
        from consensus_overlord_tpu.crypto import bls12381 as oracle

        h = sm3_hash(b"bench-block-hash")
        sks = [0xBEEF + 97 * i for i in range(n)]
        return ([oracle.sign(sk, h) for sk in sks], h,
                [oracle.sk_to_pk(sk) for sk in sks])


def main() -> int:
    ap = argparse.ArgumentParser(
        description="staged profile of TpuBlsCrypto.verify_batch")
    ap.add_argument("n", nargs="?", type=int, default=None,
                    help="batch lanes (default: 1024 on an accelerator, "
                    "8 on CPU)")
    ap.add_argument("--iters", type=int, default=4,
                    help="measured iterations after the warm-up rep")
    ap.add_argument("--json", action="store_true",
                    help="(kept for compatibility — the JSON tail is "
                    "always emitted; this silences the stderr stage "
                    "lines)")
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU lanes (the CI smoke configuration)")
    ap.add_argument("--mesh", type=int, default=0, metavar="D",
                    help="profile the mesh kernel set over a D-lane "
                    "virtual CPU mesh (implies --cpu)")
    ap.add_argument("--sharded-probe", action="store_true",
                    help="also run the mesh stage probe (partial-reduce "
                    "vs all-gather split; compiles two extra kernels)")
    ap.add_argument("--profile-dir", default=None,
                    help="capture an XLA trace of one measured batch "
                    "into this directory (ProfileSession)")
    ap.add_argument("--inject-straggler", default=None, metavar="DEVICE",
                    help="sleep inside DEVICE's shard-readback timing "
                    "window (e.g. 'cpu:3') so the straggler detector "
                    "has a seeded fault to flag — the CI fixture")
    ap.add_argument("--inject-straggler-ms", type=float, default=50.0,
                    help="injected per-shard delay in milliseconds "
                    "(default 50)")
    ap.add_argument("--straggler-ratio", type=float, default=1.5,
                    help="straggler flag ratio vs the mesh median "
                    "(<= 0 disables the detector)")
    args = ap.parse_args()

    if args.mesh:
        # Virtual-device mesh: the flag must be in place before the XLA
        # CPU backend initializes, hence before ANY jax import below.
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.mesh}").strip()
        args.cpu = True
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    from consensus_overlord_tpu.compile_cache import enable

    enable()
    import jax

    from consensus_overlord_tpu.crypto import tpu_provider as tp
    from consensus_overlord_tpu.obs import (AnomalyDetector, DeviceProfiler,
                                            FlightRecorder, Metrics,
                                            ProfileSession,
                                            StragglerDetector)

    say = (lambda *a: None) if args.json else (
        lambda *a: print(*a, file=sys.stderr, flush=True))
    platform = jax.devices()[0].platform
    n = args.n if args.n is not None else (8 if platform == "cpu" else 1024)
    say(f"device: {platform}  N={n}")

    sigs, h, pks = _fixture(n)
    mesh = None
    if args.mesh:
        from consensus_overlord_tpu.parallel import make_mesh

        mesh = make_mesh(args.mesh)
        say(f"mesh: {mesh.devices.size} lanes")
    provider = tp.TpuBlsCrypto(0xA11CE, device_threshold=min(8, n),
                               mesh=mesh)
    provider.update_pubkeys(pks)

    # Warm rep absorbs the kernel compile UNMETERED (it would dominate
    # every stage histogram; bench_round.py does the same).
    t0 = time.perf_counter()
    provider.verify_batch(sigs, [h] * n, pks)
    first_touch_s = time.perf_counter() - t0
    say(f"{'first_touch':12s} {first_touch_s * 1e3:9.2f} ms  (compile, "
        "unmetered)")

    metrics = Metrics()
    prof = DeviceProfiler(metrics)
    provider.bind_metrics(metrics)
    provider.bind_profiler(prof)
    recorder = FlightRecorder(256)
    straggler = None
    if args.straggler_ratio > 0:
        straggler = StragglerDetector(metrics=metrics, recorder=recorder,
                                      ratio=args.straggler_ratio)
        prof.attach_straggler(straggler)
    anomaly = AnomalyDetector(metrics=metrics, recorder=recorder,
                              straggler=straggler)
    if args.inject_straggler:
        provider.inject_straggler(args.inject_straggler,
                                  args.inject_straggler_ms / 1e3)
        say(f"straggler injection: {args.inject_straggler} "
            f"+{args.inject_straggler_ms:.0f} ms/shard")

    session = ProfileSession(args.profile_dir)
    trace_dir = None
    lat = []
    for rep in range(args.iters):
        capture = rep == 0 and session.available \
            and session.start(1, label=f"verify_n{n}")
        t0 = time.perf_counter()
        results = provider.verify_batch(sigs, [h] * n, pks)
        lat.append(time.perf_counter() - t0)
        if capture:
            trace_dir = session.stop()
        assert all(results), "fixture signatures must all verify"

    totals = prof.stage_totals()
    stages_ms = {}
    for stage in ("parse", "dispatch", "readback", "pairing"):
        t = totals.get(f"verify_batch/{stage}")
        if t:
            stages_ms[stage] = round(t["total_s"] / t["count"] * 1e3, 3)
            say(f"{stage:12s} {stages_ms[stage]:9.2f} ms")
    full_s = sum(lat) / len(lat)
    say(f"{'full':12s} {full_s * 1e3:9.2f} ms")
    say(f"rate: {n / full_s:.0f} verifies/s")

    sharded = None
    if args.sharded_probe:
        # The straggler detector needs a rolling median per device
        # (min_samples per device/stage), so under injection the probe
        # repeats until the seeded fault can actually flag.
        probe_reps = 3 if args.inject_straggler else 1
        for _ in range(probe_reps):
            sharded = provider.profile_sharded_stages(sigs, pks)
        say(f"{'partial_red':12s} "
            f"{sharded['partial_reduce_s'] * 1e3:9.2f} ms  "
            f"({sharded['devices']} device(s))")
        say(f"{'allgather':12s} {sharded['allgather_s'] * 1e3:9.2f} ms")
        say(f"{'pair_partial':12s} "
            f"{sharded['pairing_partial_s'] * 1e3:9.2f} ms")
        say(f"{'pair_combine':12s} "
            f"{sharded['pairing_combine_s'] * 1e3:9.2f} ms")
        for key, row in sorted((sharded.get("device_stage_s")
                                or {}).items()):
            say(f"  {key:20s} {row['last_s'] * 1e3:9.3f} ms  "
                f"(n={row['count']})")
        if straggler is not None and straggler.flagged_devices():
            say(f"stragglers flagged: "
                f"{', '.join(straggler.flagged_devices())}")

    from consensus_overlord_tpu.obs import ledger

    summary = prof.summary()
    # Ledger envelope + embedded profile block: the JSON tail is a
    # BenchRecord (value = verifies/s at this N), so profile runs
    # diff/trend against each other and against bench.py records.
    print(json.dumps(ledger.annotate({
        "metric": "verify_stage_profile",
        "value": round(n / full_s, 1),
        "unit": "verifies/s",
        "device": platform,
        "n": n,
        "iters": args.iters,
        "first_touch_ms": round(first_touch_s * 1e3, 1),
        "full_ms": round(full_s * 1e3, 3),
        "verifies_per_s": round(n / full_s, 1),
        "stages_ms": stages_ms,
        "device_pairing": provider._pairing_on_device,
        "pairing_host_fallbacks": provider.pairing_host_fallbacks,
        "mesh_devices": mesh.devices.size if mesh is not None else 0,
        "occupancy": summary["occupancy"],
        "devices": summary["devices"],
        "sharded": sharded,
        "trace_dir": trace_dir,
        # Fleet observability tail: per-device cumulative stage rows,
        # the straggler detector's verdict, and the alert tally — what
        # the nightly fleet-obs-smoke lane asserts on.
        "device_stages": prof.device_stage_totals(),
        "mesh": straggler.statusz() if straggler is not None else None,
        "stragglers": (straggler.flagged_devices()
                       if straggler is not None else []),
        "alerts_total": anomaly.alert_count(),
    }, profiler=prof)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
