"""Chain-throughput A/B: fused Pallas point ops vs the XLA curve ops.

A chain of K complete adds is the shape of every scalar ladder step.
Under XLA each field multiply's fold contraction breaks fusion, so a
point op round-trips intermediates through HBM ~30x; the fused kernel
keeps them in VMEM.  Honest timing per BASELINE.md r3 rules: fresh
random inputs each iteration, device_get barrier.

Usage: python scripts/bench_pallas_point.py [B] [K]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax
import jax.numpy as jnp
import numpy as np

B = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
K = int(sys.argv[2]) if len(sys.argv) > 2 else 8


def main():
    from consensus_overlord_tpu.compile_cache import enable
    enable()
    from consensus_overlord_tpu.ops import bls12381_groups as dev
    from consensus_overlord_tpu.ops.curve import Point
    from consensus_overlord_tpu.ops.field import BLS12_381_FQ as spec
    from consensus_overlord_tpu.ops.pallas_point import g1_add_transposed

    n = spec.n
    print(f"device: {jax.devices()[0].platform}  B={B} chain={K}",
          flush=True)
    rng = np.random.default_rng(3)

    def fresh():
        # Loose-bounded random limbs: the add formula is total, and for
        # throughput the inputs needn't be curve points.
        return [jnp.asarray(rng.integers(0, 1 << 10, (B, n), np.int32))
                for _ in range(6)]

    def xla_chain(c):
        p = Point(c[0], c[1], c[2])
        q = Point(c[3], c[4], c[5])
        for _ in range(K):
            p = dev.G1.add(p, q)
        return p.x.sum()

    fused = g1_add_transposed(spec, block_b=256)

    def pallas_chain(c):
        px, py, pz = (jnp.moveaxis(c[0], 0, 1), jnp.moveaxis(c[1], 0, 1),
                      jnp.moveaxis(c[2], 0, 1))
        qx, qy, qz = (jnp.moveaxis(c[3], 0, 1), jnp.moveaxis(c[4], 0, 1),
                      jnp.moveaxis(c[5], 0, 1))
        for _ in range(K):
            px, py, pz = fused(px, py, pz, qx, qy, qz)
        return px.sum()

    bests = {}
    for name, fn in (("xla", xla_chain), ("pallas", pallas_chain)):
        j = jax.jit(fn)
        jax.device_get(j(fresh()))  # warm
        best = None
        for _ in range(3):
            c = fresh()
            jax.block_until_ready(c)
            t0 = time.perf_counter()
            out = jax.device_get(j(c))
            dt = (time.perf_counter() - t0) * 1e3
            print(f"{name:7s} {dt:8.2f} ms  digest={int(out) & 0xffffffff}",
                  flush=True)
            best = dt if best is None or dt < best else best
        bests[name] = best
        print(f"{name}: best {best:.2f} ms "
              f"({K * B / best * 1000:.0f} adds/s)", flush=True)

    # Self-contained ledger tail: this rung's own metric, never mixed
    # into the BLS headline trend.
    import json

    from consensus_overlord_tpu.obs import ledger
    print(json.dumps(ledger.build_record(
        "ladder_pallas_point_add_ratio_vs_xla",
        round(bests["xla"] / bests["pallas"], 4), "x",
        context={"backend": jax.default_backend(), "batch": B, "chain": K,
                 "xla_ms": round(bests["xla"], 3),
                 "pallas_ms": round(bests["pallas"], 3),
                 "xla_adds_per_s": round(K * B / bests["xla"] * 1000, 1),
                 "pallas_adds_per_s":
                     round(K * B / bests["pallas"] * 1000, 1)})))


if __name__ == "__main__":
    main()
