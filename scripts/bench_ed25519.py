"""Ed25519 device-vs-host verdict (BASELINE config 2's named curve;
r4 verdict Missing #5): the device batch path exists and is tested, but
no measured row showed it WINNING anywhere — its dispatch costs ~0.8 s,
so the host C backend wins below the ~64-lane crossover, and nothing
above 64 was ever measured.  This script measures the open-loop rungs
either side of the claimed crossover and renders the verdict: a winning
device row in BASELINE.md, or a recorded negative that makes
host-by-default the documented design.

Measurement honesty: fresh RLC weights are drawn inside verify_batch on
every call (secrets.randbits), so repeated calls on the same fixture are
distinct computations through the PJRT relay's dedup.  Host rate is the
per-signature C loop (the `cryptography`/OpenSSL backend) on one core —
what a below-threshold deployment actually runs.  Without that optional
package, fixtures come from the pure-Python RFC 8032 signer
(ops/edwards.py host_sign) and the host bar is the provider's own
cofactored rule — the context block names which backend was measured.

The final stdout line is ONE self-contained perf-ledger BenchRecord
(obs/ledger.py): value = the best device rung's rate, context = every
rung, the host bars, and the crossover verdict — so `scripts/ledger.py
show/check/trend` track the lane across PRs (the ROADMAP flagged this
crossover as never recorded).

Usage: python scripts/bench_ed25519.py [rungs...]   default: 64 128 512 2048 8192
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

RUNGS = [int(a) for a in sys.argv[1:]] or [64, 128, 512, 2048, 8192]
ITERS = 5


def _fixture(n_max, h):
    """(sigs, pks) for n_max distinct signers on one message hash,
    disk-cached.  Prefers the C backend; falls back to the pure-Python
    RFC 8032 signer so the lane records without `cryptography`."""
    import numpy as np

    from consensus_overlord_tpu.ops import edwards as ed

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".cache")
    os.makedirs(cache_dir, exist_ok=True)
    cache = os.path.join(cache_dir, "ed_fixture.npz")
    if os.path.exists(cache):
        data = np.load(cache)
        if data["sigs"].shape[0] >= n_max:
            return ([bytes(r) for r in data["sigs"][:n_max]],
                    [bytes(r) for r in data["pks"][:n_max]])
        os.unlink(cache)

    seeds = [bytes([i % 251, i // 251 % 251, 7, 9] * 8)
             for i in range(n_max)]
    try:
        from consensus_overlord_tpu.crypto.provider import Ed25519Crypto

        signers = [Ed25519Crypto(s) for s in seeds]
        sigs = [s.sign(h) for s in signers]
        pks = [s.pub_key for s in signers]
    except ModuleNotFoundError:  # no `cryptography`: pure-python signer
        sigs = [ed.host_sign(s, h) for s in seeds]
        pks = [ed.host_pub_key(s) for s in seeds]
    np.savez(cache,
             sigs=np.frombuffer(b"".join(sigs), np.uint8).reshape(-1, 64),
             pks=np.frombuffer(b"".join(pks), np.uint8).reshape(-1, 32))
    return sigs, pks


def main():
    from consensus_overlord_tpu.compile_cache import enable
    enable()

    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto.ed25519_tpu import Ed25519TpuCrypto
    from consensus_overlord_tpu.obs import ledger

    h = sm3_hash(b"ed25519-bench-msg")
    sigs, pks = _fixture(max(RUNGS), h)

    try:
        dev = Ed25519TpuCrypto(b"\x07" * 32, device_threshold=1)
        host_backend = "cryptography-openssl"
    except ModuleNotFoundError:
        # Verification needs no signing key: bypass the host-backend
        # keygen in __init__ and set the one field verify_batch reads.
        dev = object.__new__(Ed25519TpuCrypto)
        dev._threshold = 1
        host_backend = "pure-python-cofactored"

    context = {"iters": ITERS, "rungs": RUNGS,
               "host_backend": host_backend}

    # Host bar #1: the C loop (what a below-threshold deployment runs)
    # — only measurable when `cryptography` is installed.
    host_rate = None
    if host_backend == "cryptography-openssl":
        from consensus_overlord_tpu.crypto.provider import Ed25519Crypto

        host = Ed25519Crypto(b"\x07" * 32)
        k = 256
        t0 = time.time()
        assert all(host.verify_signature(sigs[i], h, pks[i])
                   for i in range(k))
        host_rate = k / (time.time() - t0)
        print(f"host C loop: {host_rate:,.0f} verifies/s/core",
              flush=True)
        context["host_c_verifies_per_s"] = round(host_rate, 2)

    # Host bar #2: the cofactored rule (the provider's own
    # below-threshold and fallback path — always measurable).
    k = 64
    t0 = time.time()
    assert all(dev.verify_signature(sigs[i], h, pks[i]) for i in range(k))
    cof_rate = k / (time.time() - t0)
    print(f"host cofactored (pure py): {cof_rate:,.0f} verifies/s",
          flush=True)
    context["host_cofactored_verifies_per_s"] = round(cof_rate, 2)
    host_bar = host_rate if host_rate is not None else cof_rate

    rung_rates = {}
    for rung in RUNGS:
        s, p, hh = sigs[:rung], pks[:rung], [h] * rung
        assert all(dev.verify_batch(s, hh, p))  # warm/compile this rung
        t0 = time.time()
        for _ in range(ITERS):
            ok = dev.verify_batch(s, hh, p)
        rate = rung * ITERS / (time.time() - t0)
        assert all(ok)
        rung_rates[str(rung)] = round(rate, 2)
        print(f"device rung {rung:5d}: {rate:9,.0f} verifies/s  "
              f"({rate / host_bar:5.2f}x host)", flush=True)

    best_rung, best_rate = max(rung_rates.items(),
                               key=lambda kv: kv[1])
    crossover = next((int(r) for r in sorted(rung_rates, key=int)
                      if rung_rates[r] > host_bar), None)
    context.update({
        "device_rung_verifies_per_s": rung_rates,
        "best_rung": int(best_rung),
        "host_bar_verifies_per_s": round(host_bar, 2),
        "crossover_rung": crossover,  # None = host wins everywhere
    })
    # ONE machine-clean ledger record as the stdout tail (the committed
    # artifact gates through `scripts/ledger.py check`).
    print(json.dumps(ledger.build_record(
        "ed25519_verifies_per_sec_device_best", best_rate, "verifies/s",
        context=context,
        vs_baseline=round(best_rate / host_bar, 3))))


if __name__ == "__main__":
    main()
