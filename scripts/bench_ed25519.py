"""Ed25519 device-vs-host verdict (BASELINE config 2's named curve;
r4 verdict Missing #5): the device batch path exists and is tested, but
no measured row showed it WINNING anywhere — its dispatch costs ~0.8 s,
so the host C backend wins below the ~64-lane crossover, and nothing
above 64 was ever measured.  This script measures the open-loop rungs
either side of the claimed crossover and renders the verdict: a winning
device row in BASELINE.md, or a recorded negative that makes
host-by-default the documented design.

Measurement honesty: fresh RLC weights are drawn inside verify_batch on
every call (secrets.randbits), so repeated calls on the same fixture are
distinct computations through the PJRT relay's dedup.  Host rate is the
per-signature C loop (the `cryptography`/OpenSSL backend) on one core —
what a below-threshold deployment actually runs.

Usage: python scripts/bench_ed25519.py [rungs...]   default: 64 128 512 2048 8192
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

RUNGS = [int(a) for a in sys.argv[1:]] or [64, 128, 512, 2048, 8192]
ITERS = 5


def main():
    from consensus_overlord_tpu.compile_cache import enable
    enable()
    import numpy as np

    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto.ed25519_tpu import Ed25519TpuCrypto
    from consensus_overlord_tpu.crypto.provider import Ed25519Crypto

    n_max = max(RUNGS)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".cache")
    os.makedirs(cache_dir, exist_ok=True)
    cache = os.path.join(cache_dir, "ed_fixture.npz")
    h = sm3_hash(b"ed25519-bench-msg")
    if os.path.exists(cache):
        data = np.load(cache)
        if data["sigs"].shape[0] >= n_max:
            sigs = [bytes(r) for r in data["sigs"][:n_max]]
            pks = [bytes(r) for r in data["pks"][:n_max]]
        else:
            os.unlink(cache)
    if not os.path.exists(cache):
        signers = [Ed25519Crypto(bytes([i % 251, i // 251 % 251, 7, 9] * 8))
                   for i in range(n_max)]
        sigs = [s.sign(h) for s in signers]
        pks = [s.pub_key for s in signers]
        np.savez(cache,
                 sigs=np.frombuffer(b"".join(sigs), np.uint8).reshape(-1, 64),
                 pks=np.frombuffer(b"".join(pks), np.uint8).reshape(-1, 32))

    host = Ed25519Crypto(b"\x07" * 32)
    dev = Ed25519TpuCrypto(b"\x07" * 32, device_threshold=1)

    # Host C rate (one core), the below-threshold path.
    k = 256
    t0 = time.time()
    assert all(host.verify_signature(sigs[i], h, pks[i]) for i in range(k))
    host_rate = k / (time.time() - t0)
    print(f"host C loop: {host_rate:,.0f} verifies/s/core", flush=True)

    # Cofactored host rule (the provider's own below-threshold path).
    t0 = time.time()
    assert all(dev.verify_signature(sigs[i], h, pks[i]) for i in range(64))
    cof_rate = 64 / (time.time() - t0)
    print(f"host cofactored (pure py): {cof_rate:,.0f} verifies/s", flush=True)

    for rung in RUNGS:
        s, p, hh = sigs[:rung], pks[:rung], [h] * rung
        assert all(dev.verify_batch(s, hh, p))  # warm/compile this rung
        t0 = time.time()
        for _ in range(ITERS):
            ok = dev.verify_batch(s, hh, p)
        rate = rung * ITERS / (time.time() - t0)
        assert all(ok)
        print(f"device rung {rung:5d}: {rate:9,.0f} verifies/s  "
              f"({rate / host_rate:5.2f}x host C)", flush=True)


if __name__ == "__main__":
    main()
