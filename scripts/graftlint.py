"""graftlint CLI — run the repo's AST-based invariant checker.

    python scripts/graftlint.py                    # whole repo, human output
    python scripts/graftlint.py --json             # machine output (CI)
    python scripts/graftlint.py --rules TPU001,CONC002 path/to/file.py
    python scripts/graftlint.py --baseline graftlint_baseline.json
    python scripts/graftlint.py --write-baseline new_baseline.json
    python scripts/graftlint.py --list-rules

Stdlib-only and device-free (ast + tokenize — no jax import), so it is
safe in any CI lane.  Exit codes: 0 = clean (inline-suppressed and
baselined findings don't count), 1 = actionable findings, 2 = usage or
internal error.  Rule catalog, suppression syntax, and the baseline
workflow: consensus_overlord_tpu/analysis/README.md.
"""

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from consensus_overlord_tpu.analysis import (  # noqa: E402
    Project,
    all_rules,
    run_rules,
)
from consensus_overlord_tpu.analysis.core import write_baseline  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based invariant checker for jit purity, limb "
                    "discipline, lock/breaker rules, and the metric & "
                    "RNG contracts")
    ap.add_argument("paths", nargs="*",
                    help="explicit files for the code rules (default: "
                         "the rule's own file scope under the package)")
    ap.add_argument("--root", default=_ROOT,
                    help="repo root (default: the checkout this script "
                         "lives in)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule codes (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON on stdout")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of accepted findings (each "
                         "entry needs a reason)")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="write the current findings as a baseline "
                         "skeleton (reasons left empty for a human to "
                         "justify) and exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule codes and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code in sorted(all_rules()):
            print(code)
        return 0

    overrides = {}
    if args.paths:
        overrides["files"] = args.paths
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    project = Project(args.root, overrides=overrides)
    try:
        result = run_rules(project, rules=rules,
                           baseline_path=args.baseline)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.write_baseline, result.findings)
        print(f"graftlint: wrote {len(result.findings)} entr"
              f"{'y' if len(result.findings) == 1 else 'ies'} to "
              f"{args.write_baseline} — fill in each \"reason\" before "
              "pointing --baseline at it")
        return 0

    if args.as_json:
        print(json.dumps(result.to_dict(), indent=2))
        return result.exit_code

    for f in result.findings:
        print(f.render())
    tail = (f"{len(result.findings)} finding(s)"
            f" ({len(result.suppressed)} suppressed,"
            f" {len(result.baselined)} baselined)")
    if result.findings:
        print(f"graftlint: FAIL — {tail}")
    else:
        print(f"graftlint: ok — {tail}")
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
