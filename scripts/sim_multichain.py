"""Config-5 exercise (BASELINE.json): mixed-curve multi-chain — two
independent consensus fleets with DIFFERENT signature schemes running
concurrently in one process, sharing one TPU through their providers'
frontiers (the multi-chain shape CITA-Cloud deployments run, one
consensus service per chain; reference SURVEY.md §0).

Chain A: SM2 validators with the device-batched provider (the scheme
CITA-Cloud mainnets actually deploy).  Chain B: Ed25519 validators on
the host path (its device dispatch costs ~0.8 s/batch, so below
~64-lane coalesced batches the host C backend wins — that crossover is
the provider's own device_threshold default, and honesty beats forcing
traffic onto the chip).

Prints one JSON line per chain plus a combined line.

Usage: python scripts/sim_multichain.py [--a-validators 32]
       [--b-validators 64] [--heights 3] [--interval-ms 3000]
"""

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--a-validators", type=int, default=32)
    ap.add_argument("--b-validators", type=int, default=64)
    ap.add_argument("--heights", type=int, default=3)
    ap.add_argument("--interval-ms", type=int, default=3000)
    ap.add_argument("--device-threshold", type=int, default=4)
    ap.add_argument("--timeout", type=float, default=300.0)
    args = ap.parse_args()

    os.environ.setdefault("CONSENSUS_PAD_MIN", "32")

    from consensus_overlord_tpu.crypto.ecdsa_tpu import Sm2Crypto
    from consensus_overlord_tpu.crypto.provider import Ed25519Crypto
    from consensus_overlord_tpu.sim import SimNetwork

    # Prewarm the SM2 device kernel (first touch through the remote
    # tunnel costs ~30 s; retried via crypto/warm.py against the flaky
    # remote_compile endpoint).
    from consensus_overlord_tpu.crypto.warm import rungs_for, warm_simple
    warm = Sm2Crypto(0x7777, device_threshold=args.device_threshold)
    warm_simple(warm, rungs_for(max(args.device_threshold,
                                    args.a_validators, 8)))

    async def run_chain(name, net, heights, timeout, metrics, profiler):
        from consensus_overlord_tpu.obs import snapshot

        t0 = time.perf_counter()
        last = t0
        ms = []
        for h in range(1, heights + 1):
            await net.run_until_height(h, timeout=timeout)
            now = time.perf_counter()
            ms.append((now - last) * 1000)
            last = now
        total = time.perf_counter() - t0
        await net.stop()
        srt = sorted(ms)
        # Registry snapshot rides in the JSON tail the way sim/run.py's
        # does (count/sum/total samples; full buckets stay on /metrics)
        # so the MULTICHIP_* ledger carries batch-shape data per chain.
        scraped = snapshot(metrics.registry)
        obs = {k: v for k, v in scraped.items()
               if k.split("{", 1)[0].endswith(("_count", "_sum",
                                               "_total"))}
        return {
            "chain": name,
            "validators": len(net.nodes),
            "heights": heights,
            "total_s": round(total, 3),
            "p50_ms": round(srt[len(srt) // 2], 1),
            "p95_ms": round(srt[-1], 1),
            "delivered": net.router.delivered,
            "metrics": obs,
            "profile": profiler.summary(),
        }

    async def run() -> None:
        from consensus_overlord_tpu.obs import DeviceProfiler, Metrics

        # One registry + profiler PER CHAIN: the two fleets share a
        # process (and a TPU) but must not share histograms, or chain
        # B's host-path shape would pollute chain A's device numbers.
        metrics_a, metrics_b = Metrics(), Metrics()
        prof_a = DeviceProfiler(metrics_a)
        prof_b = DeviceProfiler(metrics_b)
        a = SimNetwork(
            n_validators=args.a_validators,
            block_interval_ms=args.interval_ms,
            crypto_factory=lambda i: Sm2Crypto(
                0x3000 + 7919 * i,
                device_threshold=args.device_threshold),
            use_frontier=True, frontier_linger_s=0.05,
            metrics=metrics_a, profiler=prof_a, sim_device_crypto=True)
        b = SimNetwork(
            n_validators=args.b_validators,
            block_interval_ms=args.interval_ms,
            crypto_factory=lambda i: Ed25519Crypto(
                (0x5000 + 7919 * i).to_bytes(4, "big") * 8),
            use_frontier=True, frontier_linger_s=0.005,
            metrics=metrics_b, profiler=prof_b, sim_device_crypto=True)
        t0 = time.perf_counter()
        a.start(init_height=1)
        b.start(init_height=1)
        ra, rb = await asyncio.gather(
            run_chain("sm2-device", a, args.heights, args.timeout,
                      metrics_a, prof_a),
            run_chain("ed25519-host", b, args.heights, args.timeout,
                      metrics_b, prof_b))
        wall = time.perf_counter() - t0
        from consensus_overlord_tpu.obs import ledger

        # Every line is a ledger entry (per-chain + combined): the
        # MULTICHIP_rNN tail self-describes like BENCH_rNN does.
        print(json.dumps(ledger.annotate({**ra, "crypto": "sm2",
                                          "tpu": True})))
        print(json.dumps(ledger.annotate({**rb, "crypto": "ed25519",
                                          "tpu": False})))
        print(json.dumps(ledger.annotate({
            "metric": "multi-chain-mixed-curve",
            "value": round(wall, 3),
            "unit": "wall_s",
            "chains": 2,
            "total_validators": args.a_validators + args.b_validators,
            "heights_per_chain": args.heights,
            "wall_s": round(wall, 3),
        })))

    asyncio.run(run())


if __name__ == "__main__":
    main()
