"""Multi-tenant crypto-as-a-service acceptance harness: M chains × N
validators in one process, every chain's signature traffic feeding ONE
SharedFrontier (crypto/tenancy.py) — the "one TPU serving many chains"
economics (ROADMAP "Crypto-as-a-service"), with one tenant deliberately
saturating its lane.

Each chain is an independent SimNetwork fleet registered as one tenant
on the shared core (tenant = chain, so all N validators of a chain feed
one lane).  The shared provider is throttled (--flush-cost-ms sleeps per
batch) so device occupancy is contended like a real chip under load.
Saturating tenants run a flood task that pumps verify traffic far past
their queue bound — admission control sheds the overflow to the
host-oracle path (exact verdicts) while DWRR keeps composing fair
batches for the light tenants.  --adversarial K makes the first K
saturators flood with INVALID signatures (the Byzantine tenant): the
run then also fails if any garbage verify came back True, batched or
shed.

The run is the acceptance test; it exits nonzero unless:

  1. every chain reaches --heights (liveness under a saturating
     neighbor — the whole point of fairness + admission control);
  2. every saturating tenant shed at least once
     (frontier_admission_sheds_total nonzero — the bound engaged);
  3. no light tenant's p50 queue wait exceeds --wait-ratio × the
     lightest light tenant's (starvation bound).

Output: one ledger-stamped BenchRecord line PER TENANT (tenant id in
the emitter context, so `scripts/ledger.py trend` can track per-tenant
throughput across PRs) plus one combined line carrying the per-tenant
status map, the shared-frontier stats, and the assertion outcomes.
--out-dir additionally writes each line to its own JSON file (the CI
artifact shape the nightly multichain-smoke job uploads).

Usage: python scripts/sim_multichain.py --chains 3 --saturate 1
       [--validators 4] [--heights 3] [--interval-ms 100] ...
"""

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


class ThrottledProvider:
    """The shared 'device': a sim-grade provider whose verify_batch
    costs a fixed wall-clock sleep per flush — contention for the chip
    is real even on CPU, so tenant queue dynamics (linger, sheds, DWRR
    shares) behave like a loaded device instead of resolving in µs."""

    def __init__(self, base, flush_cost_s: float):
        self._base = base
        self._cost = flush_cost_s

    def __getattr__(self, name):
        return getattr(self._base, name)

    def verify_batch(self, sigs, hashes, voters):
        if self._cost > 0:
            time.sleep(self._cost)
        return self._base.verify_batch(sigs, hashes, voters)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="M chains x N validators over one shared multi-tenant "
                    "frontier, with saturating tenants")
    ap.add_argument("--chains", type=int, default=3)
    ap.add_argument("--validators", type=int, default=4,
                    help="validators per chain")
    ap.add_argument("--heights", type=int, default=3,
                    help="target height per chain")
    ap.add_argument("--saturate", type=int, default=1,
                    help="how many chains flood their lane (first K)")
    ap.add_argument("--adversarial", type=int, default=0,
                    help="how many of the saturating tenants flood with "
                         "INVALID signatures (first K of --saturate): a "
                         "Byzantine tenant pumping garbage through the "
                         "shared pipeline — every verdict must come back "
                         "False (batched or shed), honest chains must "
                         "still commit, and the fairness gate must hold")
    ap.add_argument("--interval-ms", type=int, default=100)
    ap.add_argument("--max-batch", type=int, default=64,
                    help="shared frontier flush size cap")
    ap.add_argument("--linger-ms", type=float, default=10.0)
    ap.add_argument("--tenant-queue-bound", type=int, default=48,
                    help="per-tenant pending bound (arrivals over it shed "
                         "to the host oracle)")
    ap.add_argument("--tenant-weight", type=int, default=1)
    ap.add_argument("--flood-burst", type=int, default=256,
                    help="verify requests per flood burst (saturating "
                         "tenants; > queue bound so sheds engage)")
    ap.add_argument("--flood-pause-ms", type=float, default=10.0)
    ap.add_argument("--flush-cost-ms", type=float, default=1.0,
                    help="simulated device cost per batch flush")
    ap.add_argument("--wait-ratio", type=float, default=3.0,
                    help="max allowed light-tenant p50 queue-wait ratio")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-chain liveness timeout (s)")
    ap.add_argument("--out-dir", default=None,
                    help="also write each ledger line to its own JSON file")
    args = ap.parse_args()
    if args.saturate >= args.chains:
        ap.error("--saturate must leave at least one light chain")
    if args.adversarial > args.saturate:
        ap.error("--adversarial tenants are a subset of --saturate")

    from consensus_overlord_tpu.core.sm3 import sm3_hash
    from consensus_overlord_tpu.crypto.provider import sim_crypto
    from consensus_overlord_tpu.crypto.tenancy import SharedFrontier
    from consensus_overlord_tpu.obs import Metrics, ledger, snapshot
    from consensus_overlord_tpu.sim import SimNetwork

    async def flood(lane, stop: asyncio.Event, burst: int, pause_s: float,
                    counters: dict, adversarial: bool = False) -> None:
        """Saturating-tenant load: bursts of gossip-class verifies far
        past the lane's queue bound.  Valid-signature floods prove flow
        control under honest overload (verdicts stay exact on the shed
        path); adversarial floods pump INVALID signatures — the
        Byzantine-tenant case — and every verdict must come back False
        whether it rode a device batch or shed to the host oracle."""
        crypto = sim_crypto(b"\x5a" * 32)
        h = sm3_hash(b"flood-traffic")
        sig = b"\x00" * len(crypto.sign(h)) if adversarial \
            else crypto.sign(h)
        voter = crypto.pub_key
        msg_type = "flood_adversarial" if adversarial else "flood"
        while not stop.is_set():
            results = await asyncio.gather(
                *(lane.verify(sig, h, voter, msg_type=msg_type)
                  for _ in range(burst)))
            counters["sent"] += len(results)
            counters["ok"] += sum(results)
            try:
                await asyncio.wait_for(stop.wait(), pause_s)
            except asyncio.TimeoutError:
                pass

    async def run() -> int:
        metrics = Metrics()
        shared_provider = ThrottledProvider(sim_crypto(b"\x11" * 32),
                                            args.flush_cost_ms / 1000.0)
        shared = SharedFrontier(shared_provider, max_batch=args.max_batch,
                                linger_s=args.linger_ms / 1000.0,
                                metrics=metrics)
        chains = []
        for i in range(args.chains):
            tid = f"chain{i}"
            lane = shared.register(tid, weight=args.tenant_weight,
                                   queue_bound=args.tenant_queue_bound)
            net = SimNetwork(
                n_validators=args.validators,
                block_interval_ms=args.interval_ms,
                seed=1000 + i,
                crypto_factory=(lambda j, i=i: sim_crypto(
                    ((0x6000 + 257 * i) * 4099 + j).to_bytes(4, "big") * 8)),
                use_frontier=True, metrics=metrics,
                frontier_factory=lambda crypto, lane=lane: lane)
            chains.append({"tenant": tid, "lane": lane, "net": net,
                           "saturating": i < args.saturate,
                           "adversarial": i < args.adversarial,
                           "reached": False, "total_s": None})

        stop_flood = asyncio.Event()
        flood_counters = {"sent": 0, "ok": 0}
        # Adversarial floods tally separately: their "ok" count must
        # stay ZERO (an accepted garbage signature would be a forgery
        # through the shared pipeline).
        adv_counters = {"sent": 0, "ok": 0}
        t0 = time.perf_counter()
        for c in chains:
            c["net"].start(init_height=1)
        flood_tasks = []
        for c in chains:
            if not c["saturating"]:
                continue
            counters = adv_counters if c["adversarial"] else flood_counters
            flood_tasks.append(asyncio.get_running_loop().create_task(
                flood(c["lane"], stop_flood, args.flood_burst,
                      args.flood_pause_ms / 1000.0, counters,
                      adversarial=c["adversarial"])))

        async def run_chain(c) -> None:
            start = time.perf_counter()
            await c["net"].run_until_height(args.heights,
                                            timeout=args.timeout)
            c["total_s"] = round(time.perf_counter() - start, 3)
            c["reached"] = True

        failures = []
        results = await asyncio.gather(*(run_chain(c) for c in chains),
                                       return_exceptions=True)
        stop_flood.set()
        for task in flood_tasks:
            task.cancel()
        await asyncio.gather(*flood_tasks, return_exceptions=True)
        for c, r in zip(chains, results):
            if isinstance(r, BaseException):
                failures.append(
                    f"LIVENESS: {c['tenant']} missed height "
                    f"{args.heights} within {args.timeout}s ({r!r})")
        wall = time.perf_counter() - t0
        for c in chains:
            await c["net"].stop()
        shared.close()
        # Let the shutdown drain's in-flight batches resolve before the
        # loop closes (close() schedules the worker release async).
        await asyncio.sleep(0.05)

        # -- acceptance: sheds engaged on every saturating tenant ---------
        for c in chains:
            s = c["lane"].tenant_stats
            if c["saturating"] and s.sheds == 0:
                failures.append(
                    f"ADMISSION: saturating tenant {c['tenant']} never "
                    f"shed (bound {args.tenant_queue_bound} too high or "
                    f"flood too weak; requests={s.requests})")

        # -- acceptance: adversarial floods were all rejected -------------
        if adv_counters["ok"] > 0:
            failures.append(
                f"FORGERY: {adv_counters['ok']} of "
                f"{adv_counters['sent']} invalid-signature flood "
                f"verifies came back True")
        if args.adversarial and adv_counters["sent"] == 0:
            failures.append("ADVERSARIAL: flood task sent nothing")

        # -- acceptance: light-tenant p50 queue-wait starvation bound -----
        light = [c for c in chains if not c["saturating"]]
        p50s = {c["tenant"]: c["lane"].tenant_stats.p50_wait_ms()
                for c in light}
        measured = {t: p for t, p in p50s.items() if p is not None}
        if len(measured) != len(light):
            failures.append(f"FAIRNESS: light tenant with no queue-wait "
                            f"samples ({p50s})")
        elif len(measured) > 1:
            # Floor the reference at 1 ms: with sub-ms p50s the ratio is
            # scheduler jitter, not starvation.
            floor = max(min(measured.values()), 1.0)
            for t, p in measured.items():
                if p > args.wait_ratio * floor:
                    failures.append(
                        f"FAIRNESS: {t} p50 queue-wait {p:.2f}ms exceeds "
                        f"{args.wait_ratio}x the lightest tenant's "
                        f"({floor:.2f}ms)")

        # -- per-tenant ledger records + combined line --------------------
        out_dir = args.out_dir
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)

        def emit(record: dict, name: str) -> None:
            print(json.dumps(record))
            if out_dir:
                with open(os.path.join(out_dir, name + ".json"), "w") as f:
                    json.dump(record, f, indent=2)

        for c in chains:
            status = c["lane"].status()
            rate = (status["requests"] / wall) if wall > 0 else 0.0
            emit(ledger.annotate({
                "metric": "tenant-verify-throughput",
                "value": round(rate, 2),
                "unit": "verifies/s",
                "context": {
                    "tenant": c["tenant"],
                    "saturating": c["saturating"],
                    "adversarial": c["adversarial"],
                    "chains": args.chains,
                    "validators_per_chain": args.validators,
                    "heights": args.heights,
                    "queue_bound": args.tenant_queue_bound,
                    "weight": args.tenant_weight,
                },
                "tenant": status,
                "reached_height": c["reached"],
                "chain_total_s": c["total_s"],
            }), f"tenant_{c['tenant']}")

        scraped = snapshot(metrics.registry)
        obs = {k: v for k, v in scraped.items()
               if k.split("{", 1)[0].endswith(("_count", "_sum", "_total"))}
        emit(ledger.annotate({
            "metric": "multichain-shared-frontier",
            "value": round(wall, 3),
            "unit": "wall_s",
            "context": {
                "chains": args.chains,
                "saturating": args.saturate,
                "validators_per_chain": args.validators,
                "heights_per_chain": args.heights,
                "max_batch": args.max_batch,
                "linger_ms": args.linger_ms,
                "flush_cost_ms": args.flush_cost_ms,
                "queue_bound": args.tenant_queue_bound,
            },
            "tenants": shared.tenants_status(),
            "frontier": {
                "requests": shared.stats.requests,
                "batches": shared.stats.batches,
                "mean_batch": round(shared.stats.mean_batch, 2),
                "max_batch": shared.stats.max_batch,
                "failures": shared.stats.failures,
            },
            "flood": flood_counters,
            "adversarial_flood": adv_counters,
            "light_p50_wait_ms": p50s,
            "failures": failures,
            "ok": not failures,
            "metrics": obs,
        }), "multichain_combined")

        if failures:
            for f in failures:
                print(f, file=sys.stderr)
            return 2
        return 0

    sys.exit(asyncio.run(run()))


if __name__ == "__main__":
    main()
