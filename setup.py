"""Package build for consensus_overlord_tpu (used by the Dockerfile and CI;
the C extension in csrc/ is optional — the pure-JAX/Python paths cover every
capability, the extension accelerates host-side crypto)."""

from setuptools import find_packages, setup

setup(
    name="consensus_overlord_tpu",
    version="0.2.0",
    description=("TPU-native BFT consensus framework with the capabilities "
                 "of cita-cloud/consensus_overlord"),
    packages=find_packages(include=["consensus_overlord_tpu",
                                    "consensus_overlord_tpu.*"]),
    python_requires=">=3.10",
    install_requires=[],  # jax/grpcio/protobuf provided by the image/env
)
